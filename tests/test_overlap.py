"""Plan/flush overlap pipeline: window intersection, stage-ledger
accounting, and error surfacing (ISSUE 16)."""
import time

import numpy as np
import pytest

from aws_global_accelerator_controller_tpu.parallel.fleet_plan import (
    ResidentFleetPlanner,
)
from aws_global_accelerator_controller_tpu.parallel.overlap import (
    PlanFlushPipeline,
)
from aws_global_accelerator_controller_tpu.reconcile.columnar import (
    GroupState,
)
from aws_global_accelerator_controller_tpu.reconcile.resident import (
    ResidentFleet,
)
from aws_global_accelerator_controller_tpu.tracing import (
    ConvergenceLedger,
)

F = 8


def build_fleet(n=32, shards=4):
    rng = np.random.default_rng(0)
    fleet = ResidentFleet(shards=shards, endpoints_cap=4,
                          feature_dim=F)
    for i in range(n):
        fleet.upsert(GroupState(
            key=f"g{i}", group_arn=f"eg{i}", desired=[f"e{i}"],
            observed=[], observed_weights=[],
            features=rng.standard_normal((1, F)).astype(np.float32),
            fingerprint=i + 1, shard=i % shards))
    return fleet


def test_overlap_windows_intersect_and_ledger_has_stages():
    """Wave N's flush window must overlap wave N+1's plan window (the
    whole point of the pipeline), and every mutated key's trace must
    reach the ledger with the canonical stages attributed."""
    fleet = build_fleet()
    planner = ResidentFleetPlanner(fleet, seed=0)
    planner.plan_wave()                       # absorb the build wave
    ledger = ConvergenceLedger()
    rng = np.random.default_rng(1)

    def flush(wave):
        time.sleep(0.05)                      # the simulated wire

    with PlanFlushPipeline(planner, flush, ledger=ledger) as pipe:
        for _ in range(4):
            keys = [f"g{int(rng.integers(32))}" for _ in range(3)]
            for k in keys:
                fleet.note_dirty(k)
            pipe.submit_wave(keys)
    assert pipe.overlap_seconds() > 0.0
    report = pipe.window_report()
    assert len(report) == 4
    assert all("flush_end" in w for w in report)
    pct = ledger.percentiles()
    for stage in ("queued", "planned", "coalesced", "inflight",
                  "baked"):
        assert stage in pct, stage


def test_flush_completion_releases_retired_buffer():
    fleet = build_fleet(n=8)
    planner = ResidentFleetPlanner(fleet, seed=0)
    planner.plan_wave()
    front0 = planner.ring.front
    with PlanFlushPipeline(planner, lambda wave: None) as pipe:
        fleet.note_dirty("g0")
        pipe.submit_wave(["g0"])
    # close() drained the flush: the pre-wave buffer was retired and
    # then released by flush_complete
    assert planner.ring.front is not front0
    assert planner.ring._retired is None


def test_flush_error_surfaces_at_driver():
    fleet = build_fleet(n=8)
    planner = ResidentFleetPlanner(fleet, seed=0)
    planner.plan_wave()

    def boom(wave):
        raise RuntimeError("wire down")

    pipe = PlanFlushPipeline(planner, boom)
    fleet.note_dirty("g1")
    pipe.submit_wave(["g1"])
    with pytest.raises(RuntimeError, match="wire down"):
        pipe.close()


def test_zero_dirty_wave_flows_through_pipeline():
    """A steady-state wave with nothing dirty still hands off cleanly
    (flush sees an empty wave; no device work)."""
    fleet = build_fleet(n=8)
    planner = ResidentFleetPlanner(fleet, seed=0)
    planner.plan_wave()
    seen = []
    with PlanFlushPipeline(planner, seen.append) as pipe:
        w = pipe.submit_wave([])
    assert not w.device_call
    assert len(seen) == 1 and seen[0].dirty_groups == 0
