"""Pallas flash attention vs the dense oracle (interpret mode on CPU)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from aws_global_accelerator_controller_tpu.ops.pallas_attention import (
    flash_attention,
)
from aws_global_accelerator_controller_tpu.parallel.ring_attention import (
    attention_reference,
)


def _qkv(t, h, d, seed=0, dtype=jnp.float32):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return tuple(jax.random.normal(k, (t, h, d), dtype=dtype) for k in ks)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("t,h,d", [
    (64, 2, 16),     # single block, padded everywhere
    (128, 1, 128),   # exact tile fit
    (200, 2, 40),    # ragged T: padded query rows + masked padded keys
    (384, 1, 64),    # multiple q and k blocks
])
def test_matches_dense_oracle(t, h, d, causal):
    q, k, v = _qkv(t, h, d, seed=t + int(causal))
    got = flash_attention(q, k, v, causal=causal)
    want = attention_reference(q, k, v, causal=causal)
    assert got.shape == (t, h, d)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_small_blocks_multi_block_sweep():
    q, k, v = _qkv(96, 2, 8, seed=9)
    got = flash_attention(q, k, v, causal=True, block_q=32, block_k=32)
    want = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_bfloat16_accumulates_in_float32():
    q, k, v = _qkv(64, 2, 32, seed=3)
    got = flash_attention(*(x.astype(jnp.bfloat16) for x in (q, k, v)))
    assert got.dtype == jnp.bfloat16
    want = attention_reference(q, k, v)
    np.testing.assert_allclose(np.asarray(got, dtype=np.float32),
                               np.asarray(want), rtol=5e-2, atol=5e-2)


def test_stats_merge_equals_full_attention():
    """Two stats calls over disjoint key halves, merged with the flash
    recurrence, must equal attention over the concatenated keys."""
    from aws_global_accelerator_controller_tpu.ops.pallas_attention import (
        flash_attention_stats,
    )

    q, k, v = _qkv(64, 2, 16, seed=21)
    qh, kh, vh = (jnp.transpose(x, (1, 0, 2)) for x in (q, k, v))
    o1, m1, l1 = flash_attention_stats(qh, kh[:, :32], vh[:, :32])
    o2, m2, l2 = flash_attention_stats(qh, kh[:, 32:], vh[:, 32:])
    m12 = jnp.maximum(m1, m2)
    a, b = jnp.exp(m1 - m12), jnp.exp(m2 - m12)
    merged = ((o1 * a[..., None] + o2 * b[..., None])
              / (l1 * a + l2 * b)[..., None])
    want = attention_reference(q, k, v)
    np.testing.assert_allclose(
        np.asarray(jnp.transpose(merged, (1, 0, 2))), np.asarray(want),
        rtol=2e-5, atol=2e-5)


def test_causal_prefix_invariance():
    """Causal output at position p must not change when the suffix after
    p changes — the block-skip logic must not leak future blocks."""
    q, k, v = _qkv(160, 1, 16, seed=5)
    base = flash_attention(q, k, v, causal=True, block_q=64, block_k=64)
    k2 = k.at[150:].add(3.0)
    v2 = v.at[150:].add(3.0)
    out = flash_attention(q, k2, v2, causal=True, block_q=64, block_k=64)
    np.testing.assert_allclose(np.asarray(out[:150]),
                               np.asarray(base[:150]),
                               rtol=2e-5, atol=2e-5)
    assert not np.allclose(np.asarray(out[159]), np.asarray(base[159]))
