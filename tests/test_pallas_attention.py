"""Pallas flash attention vs the dense oracle (interpret mode on CPU)."""
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from aws_global_accelerator_controller_tpu.ops import (
    pallas_attention as pa,
)
from aws_global_accelerator_controller_tpu.ops.pallas_attention import (
    flash_attention,
)
from aws_global_accelerator_controller_tpu.parallel.ring_attention import (
    attention_reference,
)


def _qkv(t, h, d, seed=0, dtype=jnp.float32):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return tuple(jax.random.normal(k, (t, h, d), dtype=dtype) for k in ks)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("t,h,d", [
    (64, 2, 16),     # single block, padded everywhere
    (128, 1, 128),   # exact tile fit
    (200, 2, 40),    # ragged T: padded query rows + masked padded keys
    (384, 1, 64),    # multiple q and k blocks
])
def test_matches_dense_oracle(t, h, d, causal):
    q, k, v = _qkv(t, h, d, seed=t + int(causal))
    got = flash_attention(q, k, v, causal=causal)
    want = attention_reference(q, k, v, causal=causal)
    assert got.shape == (t, h, d)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_small_blocks_multi_block_sweep():
    q, k, v = _qkv(96, 2, 8, seed=9)
    got = flash_attention(q, k, v, causal=True, block_q=32, block_k=32)
    want = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_bfloat16_accumulates_in_float32():
    q, k, v = _qkv(64, 2, 32, seed=3)
    got = flash_attention(*(x.astype(jnp.bfloat16) for x in (q, k, v)))
    assert got.dtype == jnp.bfloat16
    want = attention_reference(q, k, v)
    np.testing.assert_allclose(np.asarray(got, dtype=np.float32),
                               np.asarray(want), rtol=5e-2, atol=5e-2)


def test_stats_merge_equals_full_attention():
    """Two stats calls over disjoint key halves, merged with the flash
    recurrence, must equal attention over the concatenated keys."""
    from aws_global_accelerator_controller_tpu.ops.pallas_attention import (
        flash_attention_stats,
    )

    q, k, v = _qkv(64, 2, 16, seed=21)
    qh, kh, vh = (jnp.transpose(x, (1, 0, 2)) for x in (q, k, v))
    o1, m1, l1 = flash_attention_stats(qh, kh[:, :32], vh[:, :32])
    o2, m2, l2 = flash_attention_stats(qh, kh[:, 32:], vh[:, 32:])
    m12 = jnp.maximum(m1, m2)
    a, b = jnp.exp(m1 - m12), jnp.exp(m2 - m12)
    merged = ((o1 * a[..., None] + o2 * b[..., None])
              / (l1 * a + l2 * b)[..., None])
    want = attention_reference(q, k, v)
    np.testing.assert_allclose(
        np.asarray(jnp.transpose(merged, (1, 0, 2))), np.asarray(want),
        rtol=2e-5, atol=2e-5)


def _oracle_grads(q, k, v, causal, cot):
    """Gradients of <attention_reference(q,k,v), cot> wrt q, k, v."""
    def f(q, k, v):
        return jnp.sum(attention_reference(q, k, v, causal=causal) * cot)
    return jax.grad(f, argnums=(0, 1, 2))(q, k, v)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("t,h,d", [
    (64, 2, 16),     # single block
    (200, 2, 40),    # ragged T: padded query rows + masked padded keys
    (384, 1, 64),    # multiple q and k blocks
])
def test_grads_match_dense_oracle(t, h, d, causal):
    q, k, v = _qkv(t, h, d, seed=100 + t + int(causal))
    cot = jax.random.normal(jax.random.PRNGKey(7), (t, h, d))

    def f(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal) * cot)

    got = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
    want = _oracle_grads(q, k, v, causal, cot)
    for g, w, name in zip(got, want, "qkv"):
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(w), rtol=2e-4, atol=2e-4,
            err_msg=f"d{name} mismatch (t={t}, h={h}, d={d}, "
                    f"causal={causal})")


def test_grads_small_blocks():
    q, k, v = _qkv(96, 2, 8, seed=31)
    cot = jnp.ones((96, 2, 8))

    def f(impl, *args):
        return jnp.sum(impl(*args) * cot)

    got = jax.grad(
        lambda q, k, v: f(lambda *a: flash_attention(
            *a, causal=True, block_q=32, block_k=32), q, k, v),
        argnums=(0, 1, 2))(q, k, v)
    want = _oracle_grads(q, k, v, True, cot)
    for g, w in zip(got, want):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   rtol=2e-4, atol=2e-4)


def test_grads_bfloat16():
    """bf16 training path: grads come back bf16 and close to the f32
    oracle at bf16 tolerance."""
    q, k, v = _qkv(128, 2, 32, seed=13)
    qb, kb, vb = (x.astype(jnp.bfloat16) for x in (q, k, v))

    def f(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True)
                       .astype(jnp.float32))

    got = jax.grad(f, argnums=(0, 1, 2))(qb, kb, vb)
    want = _oracle_grads(q, k, v, True, jnp.ones_like(q))
    for g, w in zip(got, want):
        assert g.dtype == jnp.bfloat16
        np.testing.assert_allclose(np.asarray(g, dtype=np.float32),
                                   np.asarray(w), rtol=1e-1, atol=5e-2)


def test_value_and_grad_jits_end_to_end():
    """The custom VJP must compose with jit+grad the way train_step
    uses it (no tracer leaks, stable output)."""
    q, k, v = _qkv(64, 1, 16, seed=44)

    @jax.jit
    def loss(q, k, v):
        return jnp.mean(flash_attention(q, k, v, causal=True) ** 2)

    val, grads = jax.value_and_grad(loss, argnums=(0, 1, 2))(q, k, v)
    val2, _ = jax.value_and_grad(loss, argnums=(0, 1, 2))(q, k, v)
    assert np.isfinite(float(val)) and float(val) == float(val2)
    assert all(np.all(np.isfinite(np.asarray(g))) for g in grads)


def test_causal_prefix_invariance():
    """Causal output at position p must not change when the suffix after
    p changes — the block-skip logic must not leak future blocks."""
    q, k, v = _qkv(160, 1, 16, seed=5)
    base = flash_attention(q, k, v, causal=True, block_q=64, block_k=64)
    k2 = k.at[150:].add(3.0)
    v2 = v.at[150:].add(3.0)
    out = flash_attention(q, k2, v2, causal=True, block_q=64, block_k=64)
    np.testing.assert_allclose(np.asarray(out[:150]),
                               np.asarray(base[:150]),
                               rtol=2e-5, atol=2e-5)
    assert not np.allclose(np.asarray(out[159]), np.asarray(base[159]))


# -- measured block table (VERDICT r2 weak #2 plumbing) ---------------------


def test_tuned_block_table_drives_auto_blocks(tmp_path, monkeypatch):
    """A committed sweep table overrides the heuristic for covered
    sequence lengths, clamped so a T=2048 tuning never inflates tiny
    windows; explicit args and uncovered lengths keep today's
    behavior."""
    from aws_global_accelerator_controller_tpu.ops import (
        pallas_attention as pa,
    )

    table = tmp_path / "flash_blocks.json"
    table.write_text(json.dumps({"bands": [
        {"t_max": 4096, "block_q": 512, "block_k": 1024},
    ]}))
    monkeypatch.setattr(pa, "_TUNED_PATH", str(table))
    pa._reset_tuned_cache()
    try:
        # covered band, square: table wins
        assert pa._resolve_blocks(2048, 2048, None, None) == (512, 1024)
        # clamped: tuned 512/1024 never exceeds the heuristic for T=128
        assert pa._resolve_blocks(128, 128, None, None) == (128, 128)
        # uncovered band: heuristic
        assert pa._resolve_blocks(8192, 8192, None, None) == (1024, 1024)
        # explicit args always win
        assert pa._resolve_blocks(2048, 2048, 256, None) == (256, 1024)
        # non-square (ring attention partials): heuristic per side
        assert pa._resolve_blocks(2048, 256, None, None) == (1024, 256)
    finally:
        pa._reset_tuned_cache()


def test_no_table_means_heuristic(monkeypatch, tmp_path):
    from aws_global_accelerator_controller_tpu.ops import (
        pallas_attention as pa,
    )

    monkeypatch.setattr(pa, "_TUNED_PATH",
                        str(tmp_path / "missing.json"))
    pa._reset_tuned_cache()
    try:
        assert pa._resolve_blocks(2048, 2048, None, None) == (1024, 1024)
        assert pa._resolve_blocks(100, 100, None, None) == (112, 112)
    finally:
        pa._reset_tuned_cache()


def test_tuned_table_numerics_equivalent(tmp_path, monkeypatch):
    """Block sizes are a scheduling choice: a tuned table changes only
    the rescale boundaries of the online softmax, so outputs agree to
    bf16 rounding."""
    from aws_global_accelerator_controller_tpu.ops import (
        pallas_attention as pa,
    )

    keys = jax.random.split(jax.random.PRNGKey(3), 3)
    q, k, v = (jax.random.normal(kk, (256, 2, 64), jnp.bfloat16)
               for kk in keys)
    base = np.asarray(pa.flash_attention(q, k, v, causal=True))

    table = tmp_path / "flash_blocks.json"
    table.write_text(json.dumps({"bands": [
        {"t_max": 512, "block_q": 128, "block_k": 64},
    ]}))
    monkeypatch.setattr(pa, "_TUNED_PATH", str(table))
    pa._reset_tuned_cache()
    try:
        tuned = np.asarray(pa.flash_attention(q, k, v, causal=True))
    finally:
        pa._reset_tuned_cache()
    np.testing.assert_allclose(
        base.astype(np.float32), tuned.astype(np.float32),
        rtol=2e-2, atol=2e-2)


def test_corrupt_tuned_table_warns_and_falls_back(tmp_path, monkeypatch,
                                                  caplog):
    """A committed-but-unreadable table silently dropping the measured
    tuning would be invisible; it must log a warning and fall back."""
    import logging

    from aws_global_accelerator_controller_tpu.ops import (
        pallas_attention as pa,
    )

    bad = tmp_path / "flash_blocks.json"
    bad.write_text("{not json")
    monkeypatch.setattr(pa, "_TUNED_PATH", str(bad))
    pa._reset_tuned_cache()
    try:
        with caplog.at_level(logging.WARNING,
                             logger=pa.logger.name):
            assert pa._resolve_blocks(2048, 2048, None, None) \
                == (1024, 1024)
        assert any("unreadable" in r.message for r in caplog.records)
    finally:
        pa._reset_tuned_cache()


def test_committed_tuned_table_is_valid_if_present():
    """If ops/flash_blocks.json is ever committed, it must parse and
    carry well-formed bands — a typo must fail CI, not silently
    disable the tuning in production."""
    import json as json_mod
    import os

    from aws_global_accelerator_controller_tpu.ops import (
        pallas_attention as pa,
    )

    if not os.path.exists(pa._TUNED_PATH):
        pytest.skip("no tuned table committed yet")
    with open(pa._TUNED_PATH) as f:
        table = json_mod.load(f)
    assert table.get("bands"), "committed table must carry bands"
    for band in table["bands"]:
        assert int(band["t_max"]) > 0
        assert int(band["block_q"]) > 0
        assert int(band["block_k"]) > 0


def test_triangular_grid_padded_t():
    """Square causal multi-block tilings take the scalar-prefetched
    triangular grid (dead upper-triangle blocks never iterated); a T
    that does not divide the block exercises the padded final K block
    inside the triangle, forward and backward."""
    q, k, v = _qkv(100, 2, 8, seed=41)
    got = flash_attention(q, k, v, causal=True, block_q=32, block_k=32)
    want = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)

    cot = jnp.ones((100, 2, 8))
    got_g = jax.grad(
        lambda q, k, v: jnp.sum(flash_attention(
            q, k, v, causal=True, block_q=32, block_k=32) * cot),
        argnums=(0, 1, 2))(q, k, v)
    want_g = _oracle_grads(q, k, v, True, cot)
    for g, w in zip(got_g, want_g):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   rtol=2e-4, atol=2e-4)


def test_triangular_grid_uneven_blocks_stay_rectangular():
    """block_q != block_k is outside the triangle's preconditions —
    the rectangular predicated grid must still produce the oracle."""
    q, k, v = _qkv(128, 2, 8, seed=43)
    got = flash_attention(q, k, v, causal=True, block_q=32, block_k=64)
    want = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_triangular_stats_path():
    """flash_attention_stats over a square causal multi-block tiling
    (the ring local leg) rides the triangular grid too: unnormalised
    o / l recover the oracle."""
    from aws_global_accelerator_controller_tpu.ops.pallas_attention import (
        flash_attention_stats,
    )

    q, k, v = _qkv(96, 2, 8, seed=47)
    qh, kh, vh = (jnp.transpose(x, (1, 0, 2)) for x in (q, k, v))
    o_un, m, l = flash_attention_stats(qh, kh, vh, causal=True,
                                       block_q=32, block_k=32)
    got = jnp.transpose(o_un / l[:, :, None], (1, 0, 2))
    want = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def _grad_triplet(t, heads=2, d=128, causal=True, seed=0, bq=None,
                  bk=None):
    """(dq, dk, dv) through the custom VJP with a random cotangent."""
    ks = jax.random.split(jax.random.PRNGKey(seed), 4)
    q, k, v = (jax.random.normal(kk, (t, heads, d), jnp.bfloat16)
               for kk in ks[:3])
    r = jax.random.normal(ks[3], (t, heads, d), jnp.float32)
    return jax.grad(
        lambda qq, kk, vv: jnp.sum(
            pa.flash_attention(qq, kk, vv, causal=causal,
                               block_q=bq, block_k=bk)
            .astype(jnp.float32) * r),
        argnums=(0, 1, 2))(q, k, v)


# (t, bq, bk, causal) covering every fused-path grid shape: default
# blocks (single-block degenerate), square causal multi-block (the
# triangle table), non-causal multi-block (rectangular), and
# unequal-block causal (rectangular — the triangle needs square
# tilings)
_FUSED_CASES = [
    (64, None, None, True),
    (96, None, None, False),
    (96, 32, 32, True),
    (96, 32, 32, False),
    (96, 32, 48, True),
]


@pytest.mark.parametrize("t,bq,bk,causal", _FUSED_CASES)
def test_fused_backward_matches_two_sweep(monkeypatch, t, bq, bk,
                                          causal):
    """The fused one-sweep backward (dq+dk+dv from one score
    recompute) must agree with the two-sweep kernels — same math,
    different accumulation order, so bf16-scale tolerance."""
    fused = _grad_triplet(t, causal=causal, bq=bq, bk=bk)
    monkeypatch.setattr(pa, "_FUSED_BWD_DQ_BYTES", 0)  # force 2-sweep
    # the budget is read at TRACE time — drop the jit cache or the
    # second call silently reuses the fused program (and the test
    # compares fused against itself)
    jax.clear_caches()
    swept = _grad_triplet(t, causal=causal, bq=bq, bk=bk)
    for name, a, b in zip("qkv", fused, swept):
        a32 = a.astype(jnp.float32)
        b32 = b.astype(jnp.float32)
        assert jnp.allclose(a32, b32, rtol=5e-2, atol=5e-2), (
            name, float(jnp.max(jnp.abs(a32 - b32))))


def test_two_sweep_fallback_above_budget(monkeypatch):
    """Over the dq VMEM budget the backward silently takes the
    two-sweep route and still matches the dense reference grads."""
    monkeypatch.setattr(pa, "_FUSED_BWD_DQ_BYTES", 0)
    jax.clear_caches()
    t, heads, d = 64, 2, 64
    ks = jax.random.split(jax.random.PRNGKey(5), 4)
    q, k, v = (jax.random.normal(kk, (t, heads, d), jnp.bfloat16)
               for kk in ks[:3])
    r = jax.random.normal(ks[3], (t, heads, d), jnp.float32)

    def loss(fn):
        return jax.grad(lambda qq: jnp.sum(
            fn(qq, k, v).astype(jnp.float32) * r))(q)

    got = loss(lambda qq, kk, vv: pa.flash_attention(
        qq, kk, vv, causal=True))
    want = loss(lambda qq, kk, vv: attention_reference(
        qq, kk, vv, causal=True))
    assert jnp.allclose(got.astype(jnp.float32),
                        want.astype(jnp.float32), rtol=5e-2,
                        atol=5e-2), float(
        jnp.max(jnp.abs(got.astype(jnp.float32)
                        - want.astype(jnp.float32))))


@pytest.mark.parametrize("bq,bk", [
    (None, None),   # single 128-block: intra-block mask
    (32, 32),       # p=48 crosses block boundaries: inter-block skip
])
def test_causal_grads_respect_prefix_locality(bq, bk):
    """With a cotangent restricted to output rows < p, causal dk/dv at
    key positions > p must be EXACTLY zero (those keys are invisible
    to every supervised row) — a mask slip in the fused one-sweep
    backward would leak gradient across the causal boundary.  Run at
    both one-block and multi-block tilings: the inter-block dead-skip
    logic only exists in the latter."""
    t, heads, d, p = 128, 2, 32, 48
    q, k, v = _qkv(t, heads, d, seed=11, dtype=jnp.bfloat16)
    r = jax.random.normal(jax.random.PRNGKey(12), (t, heads, d),
                          jnp.float32)
    r = r.at[p:].set(0.0)                     # supervise rows < p only

    def loss(kk, vv):
        return jnp.sum(flash_attention(q, kk, vv, causal=True,
                                       block_q=bq, block_k=bk)
                       .astype(jnp.float32) * r)

    dk, dv = jax.grad(loss, argnums=(0, 1))(k, v)
    assert jnp.all(dk.astype(jnp.float32)[p:] == 0.0)
    assert jnp.all(dv.astype(jnp.float32)[p:] == 0.0)
    # and the visible prefix does carry gradient
    assert float(jnp.max(jnp.abs(dv.astype(jnp.float32)[:p]))) > 0
