"""Informer cache/handler/resync tests."""
import threading
import time

from aws_global_accelerator_controller_tpu.kube.apiserver import FakeAPIServer
from aws_global_accelerator_controller_tpu.kube.client import KubeClient
from aws_global_accelerator_controller_tpu.kube.informers import (
    SharedInformerFactory,
    wait_for_cache_sync,
)
from aws_global_accelerator_controller_tpu.kube.objects import (
    ObjectMeta,
    Service,
    ServiceSpec,
)


def make_service(name, ns="default"):
    return Service(metadata=ObjectMeta(name=name, namespace=ns),
                   spec=ServiceSpec(type="LoadBalancer"))


def wait_until(pred, timeout=3.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.01)
    return False


def test_initial_list_fires_adds_and_syncs():
    api = FakeAPIServer()
    kube = KubeClient(api)
    kube.services.create(make_service("pre1"))
    kube.services.create(make_service("pre2"))

    factory = SharedInformerFactory(api, resync_period=30)
    informer = factory.services()
    adds = []
    informer.add_event_handler(add=lambda o: adds.append(o.metadata.name))
    stop = threading.Event()
    factory.start(stop)
    try:
        assert wait_for_cache_sync(stop, informer, timeout=10.0)
        assert sorted(adds) == ["pre1", "pre2"]
        assert len(informer.lister.list()) == 2
    finally:
        stop.set()


def test_watch_events_update_cache_and_handlers():
    api = FakeAPIServer()
    kube = KubeClient(api)
    factory = SharedInformerFactory(api, resync_period=30)
    informer = factory.services()
    adds, updates, deletes = [], [], []
    informer.add_event_handler(
        add=lambda o: adds.append(o.metadata.name),
        update=lambda old, new: updates.append(
            (old.metadata.annotations.get("k"), new.metadata.annotations.get("k"))),
        delete=lambda o: deletes.append(o.metadata.name),
    )
    stop = threading.Event()
    factory.start(stop)
    try:
        assert wait_for_cache_sync(stop, informer, timeout=10.0)
        svc = kube.services.create(make_service("live"))
        assert wait_until(lambda: adds == ["live"])
        svc.metadata.annotations["k"] = "v"
        kube.services.update(svc)
        assert wait_until(lambda: (None, "v") in updates)
        got = informer.lister.get("default", "live")
        assert got.metadata.annotations.get("k") == "v"
        kube.services.delete("default", "live")
        assert wait_until(lambda: deletes == ["live"])
        assert informer.lister.list() == []
    finally:
        stop.set()


def test_resync_redelivers_updates():
    api = FakeAPIServer()
    kube = KubeClient(api)
    kube.services.create(make_service("r"))
    factory = SharedInformerFactory(api, resync_period=0.1)
    informer = factory.services()
    updates = []
    informer.add_event_handler(update=lambda old, new: updates.append(new.metadata.name))
    stop = threading.Event()
    factory.start(stop)
    try:
        assert wait_for_cache_sync(stop, informer, timeout=10.0)
        assert wait_until(lambda: len(updates) >= 2, timeout=3.0), \
            "resync should re-deliver cached objects as updates"
    finally:
        stop.set()


def test_shared_informer_is_shared():
    api = FakeAPIServer()
    factory = SharedInformerFactory(api)
    assert factory.services() is factory.services()
    assert factory.ingresses() is not factory.services()
