"""Informer cache/handler/resync tests."""
import threading
import time

from aws_global_accelerator_controller_tpu.kube.apiserver import FakeAPIServer
from aws_global_accelerator_controller_tpu.kube.client import KubeClient
from aws_global_accelerator_controller_tpu.kube.informers import (
    SharedInformerFactory,
    wait_for_cache_sync,
)
from aws_global_accelerator_controller_tpu.kube.objects import (
    ObjectMeta,
    Service,
    ServiceSpec,
)


def make_service(name, ns="default"):
    return Service(metadata=ObjectMeta(name=name, namespace=ns),
                   spec=ServiceSpec(type="LoadBalancer"))


def wait_until(pred, timeout=3.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.01)
    return False


def test_initial_list_fires_adds_and_syncs():
    api = FakeAPIServer()
    kube = KubeClient(api)
    kube.services.create(make_service("pre1"))
    kube.services.create(make_service("pre2"))

    factory = SharedInformerFactory(api, resync_period=30)
    informer = factory.services()
    adds = []
    informer.add_event_handler(add=lambda o: adds.append(o.metadata.name))
    stop = threading.Event()
    factory.start(stop)
    try:
        assert wait_for_cache_sync(stop, informer, timeout=10.0)
        assert sorted(adds) == ["pre1", "pre2"]
        assert len(informer.lister.list()) == 2
    finally:
        stop.set()


def test_watch_events_update_cache_and_handlers():
    api = FakeAPIServer()
    kube = KubeClient(api)
    factory = SharedInformerFactory(api, resync_period=30)
    informer = factory.services()
    adds, updates, deletes = [], [], []
    informer.add_event_handler(
        add=lambda o: adds.append(o.metadata.name),
        update=lambda old, new: updates.append(
            (old.metadata.annotations.get("k"), new.metadata.annotations.get("k"))),
        delete=lambda o: deletes.append(o.metadata.name),
    )
    stop = threading.Event()
    factory.start(stop)
    try:
        assert wait_for_cache_sync(stop, informer, timeout=10.0)
        svc = kube.services.create(make_service("live"))
        assert wait_until(lambda: adds == ["live"])
        svc.metadata.annotations["k"] = "v"
        kube.services.update(svc)
        assert wait_until(lambda: (None, "v") in updates)
        got = informer.lister.get("default", "live")
        assert got.metadata.annotations.get("k") == "v"
        kube.services.delete("default", "live")
        assert wait_until(lambda: deletes == ["live"])
        assert informer.lister.list() == []
    finally:
        stop.set()


def test_resync_redelivers_updates():
    api = FakeAPIServer()
    kube = KubeClient(api)
    kube.services.create(make_service("r"))
    factory = SharedInformerFactory(api, resync_period=0.1)
    informer = factory.services()
    updates = []
    informer.add_event_handler(update=lambda old, new: updates.append(new.metadata.name))
    stop = threading.Event()
    factory.start(stop)
    try:
        assert wait_for_cache_sync(stop, informer, timeout=10.0)
        assert wait_until(lambda: len(updates) >= 2, timeout=3.0), \
            "resync should re-deliver cached objects as updates"
    finally:
        stop.set()


def test_shared_informer_is_shared():
    api = FakeAPIServer()
    factory = SharedInformerFactory(api)
    assert factory.services() is factory.services()
    assert factory.ingresses() is not factory.services()


def test_resync_spread_jitters_across_period_fake_clock():
    """Thundering-herd fix: resync re-deliveries are spread across the
    period at key-stable offsets, not released as one burst at the
    timer edge.  Driven with an explicit fake clock — _ResyncSpread is
    pure scheduling."""
    from aws_global_accelerator_controller_tpu.kube.informers import (
        _ResyncSpread,
    )

    period = 30.0
    keys = [f"default/svc{i:03d}" for i in range(50)]
    spread = _ResyncSpread(period, start=1000.0, keys=keys)

    # nothing due at the period start: the old code would have
    # delivered ALL keys at the edge of the previous period
    due0, wave0 = spread.due(1000.0)
    assert wave0 == 0
    assert len(due0) < len(keys) / 5, \
        f"burst at period start: {len(due0)} keys due immediately"

    # step the clock in 1s ticks: deliveries trickle out, each key
    # exactly once, at its own crc32 slot
    delivered_at = {}
    for tick in range(1, 31):
        due, wave = spread.due(1000.0 + tick)
        assert wave == 0
        for k in due:
            assert k not in delivered_at, f"{k} delivered twice"
            delivered_at[k] = tick
    assert set(delivered_at) | set(due0) == set(keys), \
        "every key must be delivered exactly once per period"
    # the spread is real: deliveries land in many distinct ticks and
    # no single tick carries the bulk of the fleet
    ticks = sorted(set(delivered_at.values()))
    assert len(ticks) >= 10, f"deliveries bunched into {len(ticks)} ticks"
    bulk = max(list(delivered_at.values()).count(t) for t in ticks)
    assert bulk < len(keys) / 2, f"{bulk} keys released in one tick"

    # offsets are key-stable: the next wave replays the same schedule
    _, wave1 = spread.due(1000.0 + period + 0.5)
    assert wave1 == 1
    redelivered = {}
    for tick in range(1, 31):
        due, _ = spread.due(1000.0 + period + tick)
        for k in due:
            redelivered[k] = tick
    for k, tick in delivered_at.items():
        if k in redelivered:
            assert abs(redelivered[k] - tick) <= 1, \
                "per-key slot must be stable across waves"

    # removed keys stop being scheduled; added keys join the spread
    gone, fresh = keys[0], "default/added"
    spread.remove_key(gone)
    spread.add_key(fresh)
    third = {}
    for tick in range(0, 31):
        due, _ = spread.due(1000.0 + 2 * period + tick)
        for k in due:
            third[k] = tick
    assert gone not in third
    assert fresh in third


def test_watch_drop_relist_diffs_missed_changes():
    """Kube-plane chaos regression (ISSUE 6 satellite): after a
    simulated watch-stream death (the fake plane's 410 Gone), objects
    deleted while disconnected must surface as DELETE deltas, objects
    created as ADDs, changed ones as UPDATEs — and unchanged objects
    must dispatch NOTHING (a relist over an idle fleet costs no
    spurious invalidation)."""
    from aws_global_accelerator_controller_tpu import metrics

    api = FakeAPIServer()
    kube = KubeClient(api)
    kube.services.create(make_service("stays"))
    changed = kube.services.create(make_service("changes"))
    kube.services.create(make_service("goes"))

    factory = SharedInformerFactory(api, resync_period=30)
    informer = factory.services()
    adds, updates, deletes = [], [], []
    informer.add_event_handler(
        add=lambda o: adds.append(o.metadata.name),
        update=lambda old, new: updates.append(new.metadata.name),
        delete=lambda o: deletes.append(o.metadata.name),
        # tagged resync handler so backstop re-deliveries stay out of
        # the update stream (the controllers' wiring shape)
        resync=lambda o, wave: None)
    stop = threading.Event()
    factory.start(stop)
    try:
        assert wait_for_cache_sync(stop, informer, timeout=10.0)
        relists_before = metrics.default_registry.counter_value(
            "watch_relists_total", {"kind": "Service"})
        adds.clear()

        # the gap: stream dies silently, then the world changes
        assert api.store("Service").partition_watch() >= 1
        changed.metadata.annotations["k"] = "v"
        kube.services.update(changed)
        kube.services.delete("default", "goes")
        kube.services.create(make_service("arrives"))
        api.store("Service").heal_watch()

        assert wait_until(lambda: deletes == ["goes"]
                          and adds == ["arrives"]
                          and updates == ["changes"]), \
            (adds, updates, deletes)
        # unchanged object: no delta of any kind
        time.sleep(0.1)
        assert "stays" not in adds + updates + deletes
        # cache converged to the fresh world
        names = sorted(o.metadata.name for o in informer.lister.list())
        assert names == ["arrives", "changes", "stays"]
        assert metrics.default_registry.counter_value(
            "watch_relists_total", {"kind": "Service"}) \
            == relists_before + 1
    finally:
        stop.set()


def test_relist_invalidates_fingerprint_of_missed_change():
    """A stale fingerprint skip cannot survive a relist: the synthetic
    UPDATE delta for an object changed while disconnected reaches the
    controller's note_event exactly like a live watch event, dropping
    the recorded fingerprint — while an unchanged object's gate stays
    warm (no spurious full resync for the idle fleet)."""
    from aws_global_accelerator_controller_tpu.reconcile.fingerprint import (  # noqa: E501
        FingerprintCache,
    )

    api = FakeAPIServer()
    kube = KubeClient(api)
    idle = kube.services.create(make_service("idle"))
    drifts = kube.services.create(make_service("drifts"))

    fp = FingerprintCache(
        "relist-test", lambda o: (o.metadata.annotations.get("k"),))
    factory = SharedInformerFactory(api, resync_period=30)
    informer = factory.services()
    # the controllers' wiring shape: real watch deltas invalidate,
    # resync re-deliveries do not
    informer.add_event_handler(
        update=lambda old, new: fp.note_event(new.key()),
        delete=lambda o: fp.note_event(o.key()),
        resync=lambda o, wave: None)
    stop = threading.Event()
    factory.start(stop)
    try:
        assert wait_for_cache_sync(stop, informer, timeout=10.0)
        fp.record(idle.key(), idle)
        fp.record(drifts.key(), drifts)
        assert fp.matches(idle.key(), idle)
        assert fp.matches(drifts.key(), drifts)

        assert api.store("Service").partition_watch() >= 1
        drifts.metadata.annotations["k"] = "v"
        updated = kube.services.update(drifts)
        api.store("Service").heal_watch()

        # the missed change's gate entry is gone (the record itself is
        # dropped, so even the OLD object no longer matches)...
        assert wait_until(lambda: not fp.matches(drifts.key(), drifts))
        assert not fp.matches(drifts.key(), updated)
        # ...while the unchanged object's gate stays warm
        assert fp.matches(idle.key(), idle)
    finally:
        stop.set()


def test_resync_spread_tagged_handler_receives_wave():
    """Handlers registering ``resync=`` get tagged (obj, wave)
    re-deliveries; plain handlers keep update(obj, obj)."""
    api = FakeAPIServer()
    kube = KubeClient(api)
    kube.services.create(make_service("tagged"))
    factory = SharedInformerFactory(api, resync_period=0.15)
    informer = factory.services()
    tagged, updates = [], []
    informer.add_event_handler(
        resync=lambda obj, wave: tagged.append((obj.metadata.name, wave)))
    informer.add_event_handler(
        update=lambda old, new: updates.append(new.metadata.name))
    stop = threading.Event()
    factory.start(stop)
    try:
        assert wait_for_cache_sync(stop, informer, timeout=10.0)
        assert wait_until(lambda: len(tagged) >= 2 and len(updates) >= 2,
                          timeout=5.0), \
            "both handler shapes must receive resync re-deliveries"
        names = {n for n, _ in tagged}
        assert names == {"tagged"}
        waves = [w for _, w in tagged]
        assert waves == sorted(waves), "wave numbers must be monotone"
        assert waves[-1] > waves[0], "wave must advance across periods"
    finally:
        stop.set()
