"""Deep traffic model + GPipe pipeline-parallel training.

The dense model is the oracle; the pipelined planner must match it
exactly (both run float32), including through training — the backward
pipeline is autodiff's transpose of the forward schedule, so trajectory
parity is the proof it is correct.  No reference analogue (SURVEY.md
§2: PP ABSENT upstream).
"""
import jax
import numpy as np
import pytest

from aws_global_accelerator_controller_tpu.models.deep import (
    DeepTrafficModel,
    synthetic_batch,
)
from aws_global_accelerator_controller_tpu.parallel import (
    ShardedPipelinePlanner,
)
from aws_global_accelerator_controller_tpu.parallel.ring import (
    make_mesh_1d,
)


def _setup(n_stages=4, groups=16, endpoints=8, hidden=32, seed=0):
    model = DeepTrafficModel(n_stages=n_stages, hidden_dim=hidden)
    params = model.init_params(jax.random.PRNGKey(seed))
    batch = synthetic_batch(jax.random.PRNGKey(seed + 1), groups=groups,
                            endpoints=endpoints)
    return model, params, batch


def test_dense_training_reduces_loss():
    model, params, batch = _setup()
    opt = model.init_opt_state(params)
    first = float(model.loss(params, batch))
    step = jax.jit(model.train_step)
    for _ in range(40):
        params, opt, loss = step(params, opt, batch)
    assert float(loss) < first


def test_depth_changes_scores():
    """Every stage contributes: zeroing the last stage's block changes
    the output (the residual path alone is not the whole model)."""
    model, params, batch = _setup()
    base = np.asarray(model.scores(params, batch.features))
    cut = dict(params)
    cut["stage_w"] = params["stage_w"].at[-1].set(0.0)
    got = np.asarray(model.scores(cut, batch.features))
    assert not np.allclose(base, got)


@pytest.fixture
def mesh():
    return make_mesh_1d(4, "stage")


def test_pipelined_scores_match_dense(mesh):
    model, params, batch = _setup(n_stages=mesh.shape["stage"])
    planner = ShardedPipelinePlanner(model, mesh, n_microbatches=4)
    sp = planner.shard_params(params)
    sb = planner.shard_batch(batch)
    got = np.asarray(planner.forward(sp, sb.features, sb.mask))
    want = np.asarray(model.forward(params, batch.features, batch.mask))
    np.testing.assert_array_equal(got, want)


@pytest.mark.parametrize("microbatches", [1, 2, 8])
def test_microbatch_count_is_schedule_only(mesh, microbatches):
    """M changes the schedule, never the math."""
    model, params, batch = _setup(n_stages=mesh.shape["stage"])
    planner = ShardedPipelinePlanner(model, mesh,
                                     n_microbatches=microbatches)
    got = np.asarray(planner.forward(planner.shard_params(params),
                                     batch.features, batch.mask))
    want = np.asarray(model.forward(params, batch.features, batch.mask))
    np.testing.assert_array_equal(got, want)


def test_pipelined_training_matches_dense_trajectory(mesh):
    """Five GPipe train steps track the dense oracle: the scan/ppermute
    transpose IS the backward pipeline."""
    model, params, batch = _setup(n_stages=mesh.shape["stage"])
    planner = ShardedPipelinePlanner(model, mesh, n_microbatches=4)

    d_params, d_opt = params, model.init_opt_state(params)
    s_params = planner.shard_params(params)
    s_opt = model.init_opt_state(s_params)
    sb = planner.shard_batch(batch)
    dense_step = jax.jit(model.train_step)

    for i in range(5):
        d_params, d_opt, d_loss = dense_step(d_params, d_opt, batch)
        s_params, s_opt, s_loss = planner.train_step(s_params, s_opt, sb)
        assert float(s_loss) == pytest.approx(float(d_loss),
                                              rel=1e-5), i
    for k in d_params:
        np.testing.assert_allclose(np.asarray(s_params[k]),
                                   np.asarray(d_params[k]),
                                   rtol=1e-4, atol=1e-5, err_msg=k)


def test_stage_params_actually_sharded(mesh):
    """Each device's HBM holds only its own stage block — the memory
    property pipeline parallelism exists for."""
    model, params, batch = _setup(n_stages=mesh.shape["stage"])
    planner = ShardedPipelinePlanner(model, mesh)
    sp = planner.shard_params(params)
    shards = sp["stage_w"].addressable_shards
    assert len(shards) == mesh.shape["stage"]
    assert all(s.data.shape == (1,) + params["stage_w"].shape[1:]
               for s in shards)


def test_rejects_stage_count_mismatch(mesh):
    model = DeepTrafficModel(n_stages=3)
    with pytest.raises(ValueError, match="stage"):
        ShardedPipelinePlanner(model, mesh)


@pytest.fixture
def mesh2d():
    import numpy as np_mod

    from jax.sharding import Mesh

    devs = np_mod.array(jax.devices()[:8]).reshape(2, 4)
    return Mesh(devs, ("data", "stage"))


def test_dp_pp_scores_match_dense(mesh2d):
    """dp x pp over a 2-D mesh: data shards stream their slice of each
    microbatch through their own stage ring; results are exact."""
    model, params, batch = _setup(n_stages=mesh2d.shape["stage"])
    planner = ShardedPipelinePlanner(model, mesh2d, n_microbatches=4,
                                     data_axis="data")
    sp = planner.shard_params(params)
    sb = planner.shard_batch(batch)
    got = np.asarray(planner.forward(sp, sb.features, sb.mask))
    want = np.asarray(model.forward(params, batch.features, batch.mask))
    np.testing.assert_array_equal(got, want)


def test_dp_pp_training_matches_dense_trajectory(mesh2d):
    """Training composes: stage grads all-reduce over 'data' via the
    shard_map transpose, so the dp x pp trajectory tracks the dense
    oracle like the pure-pipeline one does."""
    model, params, batch = _setup(n_stages=mesh2d.shape["stage"])
    planner = ShardedPipelinePlanner(model, mesh2d, n_microbatches=4,
                                     data_axis="data")
    d_params, d_opt = params, model.init_opt_state(params)
    s_params = planner.shard_params(params)
    s_opt = model.init_opt_state(s_params)
    sb = planner.shard_batch(batch)
    dense_step = jax.jit(model.train_step)
    for i in range(5):
        d_params, d_opt, d_loss = dense_step(d_params, d_opt, batch)
        s_params, s_opt, s_loss = planner.train_step(s_params, s_opt,
                                                     sb)
        assert float(s_loss) == pytest.approx(float(d_loss),
                                              rel=1e-5), i
    for k in d_params:
        np.testing.assert_allclose(np.asarray(s_params[k]),
                                   np.asarray(d_params[k]),
                                   rtol=1e-4, atol=1e-5, err_msg=k)


def test_dp_pp_batch_actually_data_sharded(mesh2d):
    """The batch lives sharded over 'data' (each replica's HBM holds
    half the groups) while stage params stay stage-sharded."""
    model, params, batch = _setup(n_stages=mesh2d.shape["stage"])
    planner = ShardedPipelinePlanner(model, mesh2d, data_axis="data")
    sb = planner.shard_batch(batch)
    g = batch.features.shape[0]
    shards = sb.features.addressable_shards
    assert {s.data.shape[0] for s in shards} == {g // 2}


def test_dp_pp_rejects_missing_axis(mesh):
    model = DeepTrafficModel(n_stages=4)
    with pytest.raises(ValueError, match="no 'data' axis"):
        ShardedPipelinePlanner(model, mesh, data_axis="data")


def test_remat_training_identical_trajectory(mesh):
    """jax.checkpoint around the stage block replays the same f32 ops,
    so remat training is numerically identical, only cheaper in
    activation memory."""
    model, params, batch = _setup(n_stages=mesh.shape["stage"])
    plain = ShardedPipelinePlanner(model, mesh, n_microbatches=4)
    remat = ShardedPipelinePlanner(model, mesh, n_microbatches=4,
                                   remat=True)
    p1, o1 = plain.shard_params(params), model.init_opt_state(
        plain.shard_params(params))
    p2, o2 = remat.shard_params(params), model.init_opt_state(
        remat.shard_params(params))
    sb1, sb2 = plain.shard_batch(batch), remat.shard_batch(batch)
    for _ in range(3):
        p1, o1, l1 = plain.train_step(p1, o1, sb1)
        p2, o2, l2 = remat.train_step(p2, o2, sb2)
        assert float(l1) == float(l2)
    for k in p1:
        np.testing.assert_array_equal(np.asarray(p1[k]),
                                      np.asarray(p2[k]), err_msg=k)


def test_dp_pp_rejects_groups_not_divisible_by_data_axis(mesh2d):
    """g=9, m=3 passes the microbatch checks (9%3==0, (3*8)%2==0) but
    cannot shard 9 groups over 2 data replicas — the planner must say
    so directly instead of failing later inside device_put with an
    opaque sharding error (ADVICE r2)."""
    model, params, _ = _setup(n_stages=mesh2d.shape["stage"])
    bad = synthetic_batch(jax.random.PRNGKey(7), groups=9, endpoints=8)
    planner = ShardedPipelinePlanner(model, mesh2d, n_microbatches=3,
                                     data_axis="data")
    sp = planner.shard_params(params)
    with pytest.raises(ValueError,
                       match=r"groups \(9\) must be divisible"):
        planner.forward(sp, bad.features, bad.mask)
