"""Fused Pallas score head vs the dense temporal head.

Interpret-mode (CPU) equivalence for the forward and every gradient,
padding-exactness on tile-hostile shapes, and the model-level
``head="fused_always"`` path end-to-end through training — the same
contract style as tests/test_pallas_attention.py.
"""
import jax
import jax.numpy as jnp
import pytest

from aws_global_accelerator_controller_tpu.models.temporal import (
    TemporalTrafficModel,
    synthetic_window,
)
from aws_global_accelerator_controller_tpu.ops.pallas_head import score_head


def _params(key, d, h, dtype=jnp.bfloat16):
    k1, k2 = jax.random.split(key)
    return {
        "w1": jax.random.normal(k1, (d, h), dtype) * 0.1,
        "b1": jnp.linspace(-0.1, 0.1, h).astype(dtype),
        "w2": jax.random.normal(k2, (h, 1), dtype) * 0.1,
        "b2": jnp.ones((1,), dtype) * 0.05,
    }


def _dense(p, x):
    h = jnp.maximum(x.astype(jnp.bfloat16) @ p["w1"] + p["b1"], 0)
    return (h @ p["w2"] + p["b2"])[..., 0].astype(jnp.float32)


SHAPES = [
    (16, 128, 128, 256),   # lane-aligned, one row block
    (64, 128, 128, 256),   # multiple row blocks
    (19, 48, 96, 200),     # everything tile-hostile
    (8, 1, 8, 16),         # tiny S=1 stream
    (24, 300, 64, 128),    # s_pad=384: raw rows-per-block not a
    #                        sublane multiple (r4 ADVICE #1)
]


def test_row_block_always_sublane_aligned():
    """_row_block must return a multiple of the f32 sublane tile or
    the forward's [bt, s_pad] output block misaligns against padded T
    — a Mosaic compile risk at exactly the padded-S shapes the
    parametrized suite can only check in interpret mode (r4 ADVICE
    #1: s_pad=384 used to yield bt=10)."""
    from aws_global_accelerator_controller_tpu.ops.pallas_head import (
        _SUBLANE,
        _row_block,
    )

    assert _row_block(4096, 384) == 8          # was 10 pre-fix
    assert _row_block(4096, 128) == 32         # benchmarked shape
    for t in (7, 8, 19, 512, 4096):
        for s_pad in (128, 256, 384, 512, 1024, 4096, 8192):
            bt = _row_block(t, s_pad)
            assert bt % _SUBLANE == 0, (t, s_pad, bt)
            assert bt >= _SUBLANE


@pytest.mark.parametrize("t,s,d,h", SHAPES)
def test_forward_matches_dense(t, s, d, h):
    p = _params(jax.random.PRNGKey(0), d, h)
    x = jax.random.normal(jax.random.PRNGKey(1), (t, s, d),
                          jnp.bfloat16)
    got = score_head(x, p["w1"], p["b1"], p["w2"], p["b2"])
    want = _dense(p, x)
    assert got.shape == (t, s) and got.dtype == jnp.float32
    assert jnp.allclose(got, want, rtol=2e-2, atol=2e-2), (
        float(jnp.max(jnp.abs(got - want))))


@pytest.mark.parametrize("t,s,d,h", SHAPES)
def test_grads_match_dense(t, s, d, h):
    p = _params(jax.random.PRNGKey(2), d, h)
    x = jax.random.normal(jax.random.PRNGKey(3), (t, s, d),
                          jnp.bfloat16)
    # random cotangent so no grad term constant-folds away (a sum
    # loss turns the dh chain into a broadcast of w2)
    r = jax.random.normal(jax.random.PRNGKey(4), (t, s), jnp.float32)

    def loss(fn, xx, pp):
        return jnp.sum(fn(pp, xx) * r)

    gx_k, gp_k = jax.grad(
        lambda xx, pp: loss(
            lambda p_, x_: score_head(x_, p_["w1"], p_["b1"],
                                      p_["w2"], p_["b2"]),
            xx, pp), argnums=(0, 1))(x, p)
    gx_d, gp_d = jax.grad(
        lambda xx, pp: loss(_dense, xx, pp), argnums=(0, 1))(x, p)

    def close(a, b, what, atol):
        a32, b32 = a.astype(jnp.float32), b.astype(jnp.float32)
        assert jnp.allclose(a32, b32, rtol=5e-2, atol=atol), (
            what, float(jnp.max(jnp.abs(a32 - b32))), atol)

    # bias grads are cancellation-heavy reductions of ~T*S bf16-scale
    # terms (the dense VJP rounds the cotangent to bf16 before
    # summing; the kernel keeps it f32) — tolerance must scale with
    # the magnitude summed, not the magnitude that survives
    sum_scale = 0.02 * float(jnp.sum(jnp.abs(r)))
    # dx: the kernel keeps the cotangent f32 through dh while the
    # dense VJP rounds it to bf16 first — at padded-S shapes (s=300)
    # the rounding-order spread peaks just under 1e-1 of max|dx| on
    # the interpret path of the installed jax (0.4.37: 8.7e-2; the
    # July toolchain peaked just above 5e-2)
    close(gx_k, gx_d, "dx",
          1e-1 * (float(jnp.max(jnp.abs(gx_d.astype(jnp.float32))))
                  + 1e-3))
    for name in ("w1", "w2"):
        scale = float(jnp.max(jnp.abs(
            gp_d[name].astype(jnp.float32)))) + 1e-3
        close(gp_k[name], gp_d[name], f"d{name}", 5e-2 * scale)
    for name in ("b1", "b2"):
        close(gp_k[name], gp_d[name], f"d{name}",
              max(sum_scale * 0.2, 1e-3))


def test_grad_dtypes_follow_params():
    p = _params(jax.random.PRNGKey(5), 128, 256)
    x = jax.random.normal(jax.random.PRNGKey(6), (16, 128, 128),
                          jnp.bfloat16)
    gx, gp = jax.grad(lambda xx, pp: jnp.sum(
        score_head(xx, pp["w1"], pp["b1"], pp["w2"], pp["b2"])
        * xx.astype(jnp.float32)[..., 0]), argnums=(0, 1))(x, p)
    assert gx.dtype == x.dtype
    for name in ("w1", "b1", "w2", "b2"):
        assert gp[name].shape == p[name].shape
        assert gp[name].dtype == p[name].dtype


def test_model_head_mode_validation():
    with pytest.raises(ValueError):
        TemporalTrafficModel(head="nope")


def test_model_2d_paths_stay_dense():
    """scores / scores_last take [S, D] reps — the fused head must not
    engage there (it is a [T, S, D] kernel)."""
    m = TemporalTrafficModel(feature_dim=8, embed_dim=32,
                             hidden_dim=64, head="fused_always")
    window, batch = synthetic_window(jax.random.PRNGKey(0), steps=16,
                                     groups=4, endpoints=4)
    params = m.init_params(jax.random.PRNGKey(1))
    got = m.scores_last(params, window)
    ref = TemporalTrafficModel(feature_dim=8, embed_dim=32,
                               hidden_dim=64, head="reference")
    want = ref.scores_last(params, window)
    assert jnp.allclose(got, want)


def test_model_sequence_training_through_fused_head():
    """Sequence-supervised training with head="fused_always" tracks
    the dense-head model: same loss trajectory within bf16 tolerance,
    and the loss actually decreases."""
    kwargs = dict(feature_dim=8, embed_dim=32, hidden_dim=64,
                  attention="reference", supervision="sequence")
    fused = TemporalTrafficModel(head="fused_always", **kwargs)
    dense = TemporalTrafficModel(head="reference", **kwargs)
    window, batch = synthetic_window(jax.random.PRNGKey(7), steps=32,
                                     groups=4, endpoints=4,
                                     per_step=True)
    pf = fused.init_params(jax.random.PRNGKey(8))
    pd = jax.tree_util.tree_map(lambda a: a, pf)
    of, od = fused.init_opt_state(pf), dense.init_opt_state(pd)
    losses_f, losses_d = [], []
    for _ in range(5):
        pf, of, lf = fused.train_step(pf, of, window, batch)
        pd, od, ld = dense.train_step(pd, od, window, batch)
        losses_f.append(float(lf))
        losses_d.append(float(ld))
    assert losses_f[-1] < losses_f[0]
    for lf, ld in zip(losses_f, losses_d):
        assert abs(lf - ld) < 5e-2, (losses_f, losses_d)


def test_remat_skipped_for_fused_head():
    """remat=True with the fused head must still train (the checkpoint
    wrap is skipped, the kernel VJP recomputes internally)."""
    m = TemporalTrafficModel(feature_dim=8, embed_dim=32,
                             hidden_dim=64, attention="reference",
                             supervision="sequence", remat=True,
                             head="fused_always")
    window, batch = synthetic_window(jax.random.PRNGKey(9), steps=16,
                                     groups=2, endpoints=4,
                                     per_step=True)
    p = m.init_params(jax.random.PRNGKey(10))
    o = m.init_opt_state(p)
    p, o, l0 = m.train_step(p, o, window, batch)
    p, o, l1 = m.train_step(p, o, window, batch)
    assert jnp.isfinite(l0) and jnp.isfinite(l1)
