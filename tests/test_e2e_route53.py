"""End-to-end: Route53 controller, including cross-controller eventual
consistency through AWS state (SURVEY.md §3.3: the Route53 controller
discovers the accelerator the GA controller created via tags and retries
until it appears)."""
import pytest

from aws_global_accelerator_controller_tpu.apis import (
    AWS_GLOBAL_ACCELERATOR_MANAGED_ANNOTATION,
    AWS_LOAD_BALANCER_TYPE_ANNOTATION,
    ROUTE53_HOSTNAME_ANNOTATION,
)
from aws_global_accelerator_controller_tpu.kube.objects import (
    LoadBalancerIngress,
    LoadBalancerStatus,
    ObjectMeta,
    Service,
    ServicePort,
    ServiceSpec,
    ServiceStatus,
)

from harness import Cluster, wait_until

NLB_HOSTNAME = "applb-0123456789abcdef.elb.ap-northeast-1.amazonaws.com"
REGION = "ap-northeast-1"


@pytest.fixture
def cluster():
    c = Cluster().start()
    yield c
    c.shutdown()


def dns_service(hostnames="www.example.com"):
    return Service(
        metadata=ObjectMeta(
            name="app", namespace="default",
            annotations={
                AWS_LOAD_BALANCER_TYPE_ANNOTATION: "external",
                AWS_GLOBAL_ACCELERATOR_MANAGED_ANNOTATION: "true",
                ROUTE53_HOSTNAME_ANNOTATION: hostnames,
            }),
        spec=ServiceSpec(type="LoadBalancer", ports=[ServicePort(port=80)]),
        status=ServiceStatus(load_balancer=LoadBalancerStatus(
            ingress=[LoadBalancerIngress(hostname=NLB_HOSTNAME)])),
    )


def records(cluster, zone_id):
    return {(r.name, r.type)
            for r in cluster.cloud.route53.list_resource_record_sets(zone_id)}


def test_records_follow_accelerator(cluster):
    """GA controller creates the accelerator; Route53 controller finds it
    by tag and creates ALIAS-A + TXT."""
    cluster.cloud.elb.register_load_balancer("applb", NLB_HOSTNAME, REGION)
    zone = cluster.cloud.route53.create_hosted_zone("example.com")
    cluster.kube.services.create(dns_service())
    wait_until(lambda: ("www.example.com.", "A") in records(cluster, zone.id),
               message="A record created")
    assert ("www.example.com.", "TXT") in records(cluster, zone.id)
    wait_until(lambda: any(e.reason == "Route53RecordCreated"
                           for e in cluster.kube.list_events()),
               message="record event")


def test_multi_hostname_annotation(cluster):
    cluster.cloud.elb.register_load_balancer("applb", NLB_HOSTNAME, REGION)
    zone = cluster.cloud.route53.create_hosted_zone("example.com")
    cluster.kube.services.create(
        dns_service("a.example.com,b.example.com"))
    wait_until(lambda: {("a.example.com.", "A"), ("b.example.com.", "A")}
               <= records(cluster, zone.id),
               message="both A records created")


def test_annotation_removal_deletes_records(cluster):
    cluster.cloud.elb.register_load_balancer("applb", NLB_HOSTNAME, REGION)
    zone = cluster.cloud.route53.create_hosted_zone("example.com")
    cluster.kube.services.create(dns_service())
    wait_until(lambda: ("www.example.com.", "A") in records(cluster, zone.id),
               message="A record created")
    svc = cluster.kube.services.get("default", "app")
    del svc.metadata.annotations[ROUTE53_HOSTNAME_ANNOTATION]
    cluster.kube.services.update(svc)
    wait_until(lambda: ("www.example.com.", "A") not in records(cluster,
                                                                zone.id),
               message="A record deleted")
    assert ("www.example.com.", "TXT") not in records(cluster, zone.id)


def test_service_delete_cleans_all_zones(cluster):
    cluster.cloud.elb.register_load_balancer("applb", NLB_HOSTNAME, REGION)
    zone1 = cluster.cloud.route53.create_hosted_zone("example.com")
    zone2 = cluster.cloud.route53.create_hosted_zone("example.org")
    cluster.kube.services.create(
        dns_service("www.example.com,www.example.org"))
    wait_until(lambda: ("www.example.com.", "A") in records(cluster, zone1.id)
               and ("www.example.org.", "A") in records(cluster, zone2.id),
               message="records in both zones")
    cluster.kube.services.delete("default", "app")
    wait_until(lambda: not records(cluster, zone1.id)
               and not records(cluster, zone2.id),
               message="all owned records deleted")


ALB_HOSTNAME = ("k8s-default-web-f1f41628db-201899272.ap-northeast-1"
                ".elb.amazonaws.com")


def test_ingress_records_follow_accelerator(cluster):
    """Ingress path: GA controller creates the accelerator for the ALB;
    Route53 controller keys off the same hostname annotation."""
    from aws_global_accelerator_controller_tpu.apis import (
        INGRESS_CLASS_ANNOTATION,
    )
    from aws_global_accelerator_controller_tpu.kube.objects import (
        Ingress,
        IngressSpec,
        IngressStatus,
    )

    cluster.cloud.elb.register_load_balancer(
        "k8s-default-web-f1f41628db", ALB_HOSTNAME, REGION,
        lb_type="application")
    zone = cluster.cloud.route53.create_hosted_zone("example.com")
    cluster.kube.ingresses.create(Ingress(
        metadata=ObjectMeta(
            name="web", namespace="default",
            annotations={
                INGRESS_CLASS_ANNOTATION: "alb",
                AWS_GLOBAL_ACCELERATOR_MANAGED_ANNOTATION: "true",
                ROUTE53_HOSTNAME_ANNOTATION: "web.example.com",
                "alb.ingress.kubernetes.io/listen-ports": '[{"HTTP": 80}]',
            }),
        spec=IngressSpec(ingress_class_name="alb"),
        status=IngressStatus(load_balancer=LoadBalancerStatus(
            ingress=[LoadBalancerIngress(hostname=ALB_HOSTNAME)])),
    ))
    wait_until(lambda: ("web.example.com.", "A") in records(cluster, zone.id),
               message="ingress A record created")
    assert ("web.example.com.", "TXT") in records(cluster, zone.id)
    cluster.kube.ingresses.delete("default", "web")
    wait_until(lambda: ("web.example.com.", "A") not in records(cluster,
                                                                zone.id),
               message="ingress records cleaned up")


# ---------------------------------------------------------------------------
# weighted record pairs (ISSUE 10: blue-green DNS via SetIdentifier)
# ---------------------------------------------------------------------------

BLUE_NLB = "bluelb-0123456789abcdef.elb.ap-northeast-1.amazonaws.com"
GREEN_NLB = "greenlb-0123456789abcdef.elb.ap-northeast-1.amazonaws.com"


def weighted_service(name, lb_hostname, set_id, weight,
                     hostname="www.example.com", extra=None):
    from aws_global_accelerator_controller_tpu.apis import (
        ROUTE53_SET_IDENTIFIER_ANNOTATION,
        ROUTE53_WEIGHT_ANNOTATION,
    )
    annotations = {
        AWS_LOAD_BALANCER_TYPE_ANNOTATION: "external",
        AWS_GLOBAL_ACCELERATOR_MANAGED_ANNOTATION: "true",
        ROUTE53_HOSTNAME_ANNOTATION: hostname,
        ROUTE53_SET_IDENTIFIER_ANNOTATION: set_id,
        ROUTE53_WEIGHT_ANNOTATION: str(weight),
    }
    annotations.update(extra or {})
    return Service(
        metadata=ObjectMeta(name=name, namespace="default",
                            annotations=annotations),
        spec=ServiceSpec(type="LoadBalancer", ports=[ServicePort(port=80)]),
        status=ServiceStatus(load_balancer=LoadBalancerStatus(
            ingress=[LoadBalancerIngress(hostname=lb_hostname)])),
    )


def weighted_records(cluster, zone_id, rtype="A"):
    return {r.set_identifier: r.weight
            for r in cluster.cloud.route53.list_resource_record_sets(zone_id)
            if r.type == rtype and r.set_identifier is not None}


def test_weighted_pair_coexists_and_cleans_up_own_side(cluster):
    """Two services claim ONE hostname as a weighted pair (distinct
    SetIdentifiers): both A records (and both ownership TXTs) coexist,
    and deleting one side removes exactly its own records."""
    cluster.cloud.elb.register_load_balancer("bluelb", BLUE_NLB, REGION)
    cluster.cloud.elb.register_load_balancer("greenlb", GREEN_NLB, REGION)
    zone = cluster.cloud.route53.create_hosted_zone("example.com")
    cluster.kube.services.create(
        weighted_service("blue", BLUE_NLB, "blue", 200))
    cluster.kube.services.create(
        weighted_service("green", GREEN_NLB, "green", 55))
    wait_until(lambda: weighted_records(cluster, zone.id)
               == {"blue": 200, "green": 55},
               message="both sides of the weighted pair")
    assert weighted_records(cluster, zone.id, "TXT").keys() \
        == {"blue", "green"}

    cluster.kube.services.delete("default", "green")
    wait_until(lambda: weighted_records(cluster, zone.id)
               == {"blue": 200},
               message="green side cleaned up alone")
    assert weighted_records(cluster, zone.id, "TXT").keys() == {"blue"}


def test_weighted_record_ramp_walks_steps_and_persists_state(cluster):
    """A weighted service declaring rollout annotations ramps its
    record weight through the declared steps (never snapping to the
    target), with the machine state persisted in the controller-owned
    rollout.agac/state annotation."""
    from aws_global_accelerator_controller_tpu.apis import (
        ROLLOUT_INTERVAL_ANNOTATION,
        ROLLOUT_STATE_ANNOTATION,
        ROLLOUT_STEPS_ANNOTATION,
    )
    from aws_global_accelerator_controller_tpu.rollout import (
        PHASE_COMPLETED,
        RolloutState,
    )

    cluster.cloud.elb.register_load_balancer("greenlb", GREEN_NLB, REGION)
    zone = cluster.cloud.route53.create_hosted_zone("example.com")
    seen = []

    def green_weight():
        w = weighted_records(cluster, zone.id).get("green")
        if w is not None and (not seen or seen[-1] != w):
            seen.append(w)
        return w

    cluster.kube.services.create(
        weighted_service("green", GREEN_NLB, "green", 200,
                         extra={ROLLOUT_STEPS_ANNOTATION: "25,50,100",
                                ROLLOUT_INTERVAL_ANNOTATION: "0.25"}))
    wait_until(lambda: green_weight() == 200, timeout=30.0,
               message="record ramp completed")
    assert seen == [50, 100, 200], f"record ramp snapped: {seen}"
    assert seen == sorted(seen)

    def persisted():
        svc = cluster.kube.services.get("default", "green")
        return RolloutState.from_json(
            svc.metadata.annotations.get(ROLLOUT_STATE_ANNOTATION))
    wait_until(lambda: persisted().phase == PHASE_COMPLETED,
               timeout=10.0, message="completion persisted")
