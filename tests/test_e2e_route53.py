"""End-to-end: Route53 controller, including cross-controller eventual
consistency through AWS state (SURVEY.md §3.3: the Route53 controller
discovers the accelerator the GA controller created via tags and retries
until it appears)."""
import pytest

from aws_global_accelerator_controller_tpu.apis import (
    AWS_GLOBAL_ACCELERATOR_MANAGED_ANNOTATION,
    AWS_LOAD_BALANCER_TYPE_ANNOTATION,
    ROUTE53_HOSTNAME_ANNOTATION,
)
from aws_global_accelerator_controller_tpu.kube.objects import (
    LoadBalancerIngress,
    LoadBalancerStatus,
    ObjectMeta,
    Service,
    ServicePort,
    ServiceSpec,
    ServiceStatus,
)

from harness import Cluster, wait_until

NLB_HOSTNAME = "applb-0123456789abcdef.elb.ap-northeast-1.amazonaws.com"
REGION = "ap-northeast-1"


@pytest.fixture
def cluster():
    c = Cluster().start()
    yield c
    c.shutdown()


def dns_service(hostnames="www.example.com"):
    return Service(
        metadata=ObjectMeta(
            name="app", namespace="default",
            annotations={
                AWS_LOAD_BALANCER_TYPE_ANNOTATION: "external",
                AWS_GLOBAL_ACCELERATOR_MANAGED_ANNOTATION: "true",
                ROUTE53_HOSTNAME_ANNOTATION: hostnames,
            }),
        spec=ServiceSpec(type="LoadBalancer", ports=[ServicePort(port=80)]),
        status=ServiceStatus(load_balancer=LoadBalancerStatus(
            ingress=[LoadBalancerIngress(hostname=NLB_HOSTNAME)])),
    )


def records(cluster, zone_id):
    return {(r.name, r.type)
            for r in cluster.cloud.route53.list_resource_record_sets(zone_id)}


def test_records_follow_accelerator(cluster):
    """GA controller creates the accelerator; Route53 controller finds it
    by tag and creates ALIAS-A + TXT."""
    cluster.cloud.elb.register_load_balancer("applb", NLB_HOSTNAME, REGION)
    zone = cluster.cloud.route53.create_hosted_zone("example.com")
    cluster.kube.services.create(dns_service())
    wait_until(lambda: ("www.example.com.", "A") in records(cluster, zone.id),
               message="A record created")
    assert ("www.example.com.", "TXT") in records(cluster, zone.id)
    wait_until(lambda: any(e.reason == "Route53RecordCreated"
                           for e in cluster.kube.list_events()),
               message="record event")


def test_multi_hostname_annotation(cluster):
    cluster.cloud.elb.register_load_balancer("applb", NLB_HOSTNAME, REGION)
    zone = cluster.cloud.route53.create_hosted_zone("example.com")
    cluster.kube.services.create(
        dns_service("a.example.com,b.example.com"))
    wait_until(lambda: {("a.example.com.", "A"), ("b.example.com.", "A")}
               <= records(cluster, zone.id),
               message="both A records created")


def test_annotation_removal_deletes_records(cluster):
    cluster.cloud.elb.register_load_balancer("applb", NLB_HOSTNAME, REGION)
    zone = cluster.cloud.route53.create_hosted_zone("example.com")
    cluster.kube.services.create(dns_service())
    wait_until(lambda: ("www.example.com.", "A") in records(cluster, zone.id),
               message="A record created")
    svc = cluster.kube.services.get("default", "app")
    del svc.metadata.annotations[ROUTE53_HOSTNAME_ANNOTATION]
    cluster.kube.services.update(svc)
    wait_until(lambda: ("www.example.com.", "A") not in records(cluster,
                                                                zone.id),
               message="A record deleted")
    assert ("www.example.com.", "TXT") not in records(cluster, zone.id)


def test_service_delete_cleans_all_zones(cluster):
    cluster.cloud.elb.register_load_balancer("applb", NLB_HOSTNAME, REGION)
    zone1 = cluster.cloud.route53.create_hosted_zone("example.com")
    zone2 = cluster.cloud.route53.create_hosted_zone("example.org")
    cluster.kube.services.create(
        dns_service("www.example.com,www.example.org"))
    wait_until(lambda: ("www.example.com.", "A") in records(cluster, zone1.id)
               and ("www.example.org.", "A") in records(cluster, zone2.id),
               message="records in both zones")
    cluster.kube.services.delete("default", "app")
    wait_until(lambda: not records(cluster, zone1.id)
               and not records(cluster, zone2.id),
               message="all owned records deleted")


ALB_HOSTNAME = ("k8s-default-web-f1f41628db-201899272.ap-northeast-1"
                ".elb.amazonaws.com")


def test_ingress_records_follow_accelerator(cluster):
    """Ingress path: GA controller creates the accelerator for the ALB;
    Route53 controller keys off the same hostname annotation."""
    from aws_global_accelerator_controller_tpu.apis import (
        INGRESS_CLASS_ANNOTATION,
    )
    from aws_global_accelerator_controller_tpu.kube.objects import (
        Ingress,
        IngressSpec,
        IngressStatus,
    )

    cluster.cloud.elb.register_load_balancer(
        "k8s-default-web-f1f41628db", ALB_HOSTNAME, REGION,
        lb_type="application")
    zone = cluster.cloud.route53.create_hosted_zone("example.com")
    cluster.kube.ingresses.create(Ingress(
        metadata=ObjectMeta(
            name="web", namespace="default",
            annotations={
                INGRESS_CLASS_ANNOTATION: "alb",
                AWS_GLOBAL_ACCELERATOR_MANAGED_ANNOTATION: "true",
                ROUTE53_HOSTNAME_ANNOTATION: "web.example.com",
                "alb.ingress.kubernetes.io/listen-ports": '[{"HTTP": 80}]',
            }),
        spec=IngressSpec(ingress_class_name="alb"),
        status=IngressStatus(load_balancer=LoadBalancerStatus(
            ingress=[LoadBalancerIngress(hostname=ALB_HOSTNAME)])),
    ))
    wait_until(lambda: ("web.example.com.", "A") in records(cluster, zone.id),
               message="ingress A record created")
    assert ("web.example.com.", "TXT") in records(cluster, zone.id)
    cluster.kube.ingresses.delete("default", "web")
    wait_until(lambda: ("web.example.com.", "A") not in records(cluster,
                                                                zone.id),
               message="ingress records cleaned up")
