"""GA diff-helper tables (reference pkg/cloudprovider/aws/global_accelerator_test.go)."""
import pytest

from aws_global_accelerator_controller_tpu.apis import (
    ALB_LISTEN_PORTS_ANNOTATION,
    AWS_GLOBAL_ACCELERATOR_NAME_ANNOTATION,
    AWS_GLOBAL_ACCELERATOR_TAGS_ANNOTATION,
)
from aws_global_accelerator_controller_tpu.cloudprovider.aws.helpers import (
    accelerator_name,
    accelerator_owner_tag_value,
    accelerator_tags_from_annotations,
    endpoint_contains_lb,
    listener_for_ingress,
    listener_for_service,
    listener_port_changed_from_service,
    listener_protocol_changed_from_service,
    tags_contains_all_values,
)
from aws_global_accelerator_controller_tpu.cloudprovider.aws.types import (
    EndpointDescription,
    EndpointGroup,
    Listener,
    LoadBalancer,
    PortRange,
)
from aws_global_accelerator_controller_tpu.kube.objects import (
    HTTPIngressPath,
    HTTPIngressRuleValue,
    Ingress,
    IngressBackend,
    IngressRule,
    IngressServiceBackend,
    IngressServiceBackendPort,
    IngressSpec,
    ObjectMeta,
    Service,
    ServicePort,
    ServiceSpec,
)


def make_service(ports, annotations=None):
    return Service(
        metadata=ObjectMeta(name="svc", namespace="ns",
                            annotations=annotations or {}),
        spec=ServiceSpec(type="LoadBalancer",
                         ports=[ServicePort(port=p, protocol=proto)
                                for p, proto in ports]))


def make_listener(ports, protocol="TCP"):
    return Listener(listener_arn="arn:l",
                    port_ranges=[PortRange(p, p) for p in ports],
                    protocol=protocol)


# -- listener_for_service / port diff (global_accelerator_test.go:15-489) --

def test_listener_for_service_tcp():
    ports, protocol = listener_for_service(make_service([(80, "TCP"), (443, "TCP")]))
    assert ports == [80, 443]
    assert protocol == "TCP"


def test_listener_for_service_udp_wins_when_last():
    ports, protocol = listener_for_service(make_service([(53, "TCP"), (53, "UDP")]))
    assert protocol == "UDP"


@pytest.mark.parametrize("listener_ports,svc_ports,changed", [
    # reference global_accelerator_test.go:157-345 table
    ([80], [80], False),                        # single port unchanged
    ([80, 443, 8080], [443, 8080, 80], False),  # multi, order-independent
    ([80], [443], True),                        # single port changed
    ([80, 8080], [443, 8080], True),            # multiple changed
    ([80, 8080], [443, 8080, 8081], True),      # increased
    ([80, 443, 8080], [443], True),             # decreased
    ([80, 443], [80, 443], False),
    ([80], [80, 443], True),
    ([], [80], True),
])
def test_listener_port_changed_from_service(listener_ports, svc_ports, changed):
    listener = make_listener(listener_ports)
    svc = make_service([(p, "TCP") for p in svc_ports])
    assert listener_port_changed_from_service(listener, svc) is changed


@pytest.mark.parametrize("listener_proto,svc_ports,changed", [
    # reference global_accelerator_test.go:15-155 table, including the
    # last-port-wins quirk for mixed-protocol Services
    ("UDP", [(53, "UDP")], False),
    ("TCP", [(80, "TCP"), (443, "TCP")], False),
    ("TCP", [(53, "UDP"), (80, "TCP")], False),  # mixed, TCP last -> TCP
    ("TCP", [(53, "UDP")], True),
    ("TCP", [(53, "UDP"), (54, "UDP")], True),
    ("TCP", [(80, "TCP"), (53, "UDP")], True),   # mixed, UDP last -> UDP
])
def test_listener_protocol_changed_from_service(listener_proto, svc_ports,
                                                changed):
    listener = make_listener([p for p, _ in svc_ports], listener_proto)
    svc = make_service(svc_ports)
    assert listener_protocol_changed_from_service(listener, svc) is changed


# -- listener_for_ingress ---------------------------------------------------

def make_ingress(annotations=None, default_port=None, rule_ports=()):
    default_backend = None
    if default_port is not None:
        default_backend = IngressBackend(service=IngressServiceBackend(
            name="d", port=IngressServiceBackendPort(number=default_port)))
    rules = []
    if rule_ports:
        rules = [IngressRule(http=HTTPIngressRuleValue(paths=[
            HTTPIngressPath(backend=IngressBackend(
                service=IngressServiceBackend(
                    name="b", port=IngressServiceBackendPort(number=p))))
            for p in rule_ports]))]
    return Ingress(metadata=ObjectMeta(name="ing", namespace="ns",
                                       annotations=annotations or {}),
                   spec=IngressSpec(default_backend=default_backend,
                                    rules=rules))


def test_listener_for_ingress_listen_ports_annotation():
    ing = make_ingress(annotations={
        ALB_LISTEN_PORTS_ANNOTATION: '[{"HTTP": 80}, {"HTTPS": 443}]'})
    ports, protocol = listener_for_ingress(ing)
    assert ports == [80, 443]
    assert protocol == "TCP"


def test_listener_for_ingress_annotation_overrides_rules():
    ing = make_ingress(annotations={
        ALB_LISTEN_PORTS_ANNOTATION: '[{"HTTPS": 443}]'},
        default_port=8080, rule_ports=(3000,))
    ports, _ = listener_for_ingress(ing)
    assert ports == [443]


def test_listener_for_ingress_bad_annotation_json():
    ing = make_ingress(annotations={ALB_LISTEN_PORTS_ANNOTATION: "not json"})
    ports, _ = listener_for_ingress(ing)
    assert ports == []


def test_listener_for_ingress_backend_ports():
    ing = make_ingress(default_port=8080, rule_ports=(3000, 3001))
    ports, _ = listener_for_ingress(ing)
    assert ports == [8080, 3000, 3001]


# -- naming / tags ----------------------------------------------------------

def test_accelerator_name_default_and_annotation():
    svc = make_service([(80, "TCP")])
    assert accelerator_name("service", svc) == "service-ns-svc"
    svc2 = make_service([(80, "TCP")], annotations={
        AWS_GLOBAL_ACCELERATOR_NAME_ANNOTATION: "custom"})
    assert accelerator_name("service", svc2) == "custom"


def test_accelerator_tags_parsing_skips_malformed():
    svc = make_service([(80, "TCP")], annotations={
        AWS_GLOBAL_ACCELERATOR_TAGS_ANNOTATION: "a=1,bad,b=2,=,c=3"})
    assert accelerator_tags_from_annotations(svc) == {
        "a": "1", "b": "2", "": "", "c": "3"}


def test_owner_tag_value():
    assert accelerator_owner_tag_value("service", "ns", "n") == "service/ns/n"


def test_tags_contains_all_values():
    tags = {"a": "1", "b": "2", "c": "3"}
    assert tags_contains_all_values(tags, {"a": "1", "b": "2"})
    assert not tags_contains_all_values(tags, {"a": "1", "x": "9"})
    assert not tags_contains_all_values(tags, {"a": "wrong"})
    assert tags_contains_all_values(tags, {})


def test_endpoint_contains_lb():
    lb = LoadBalancer(load_balancer_arn="arn:lb1", load_balancer_name="l",
                      dns_name="d")
    eg = EndpointGroup(endpoint_group_arn="arn:eg",
                       endpoint_descriptions=[EndpointDescription("arn:lb1")])
    assert endpoint_contains_lb(eg, lb)
    eg2 = EndpointGroup(endpoint_group_arn="arn:eg")
    assert not endpoint_contains_lb(eg2, lb)
