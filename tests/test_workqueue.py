"""Workqueue semantics tests (client-go invariants the controllers rely on).

Every queue-level test runs against BOTH implementations — the pure-Python
RateLimitingQueue and the native C++ one (native/workqueue.cpp via ctypes)
— since new_rate_limiting_queue may hand controllers either.
"""
import threading
import time

import pytest

from aws_global_accelerator_controller_tpu.kube.workqueue import (
    BucketRateLimiter,
    ItemExponentialFailureRateLimiter,
    RateLimitingQueue,
    new_rate_limiting_queue,
)
from aws_global_accelerator_controller_tpu.kube.native_workqueue import (
    NativeRateLimitingQueue,
    native_available,
)

IMPLS = ["python", "native"]


@pytest.fixture(params=IMPLS)
def q(request):
    """A queue with a fast limiter so tests don't sleep long."""
    if request.param == "native":
        if not native_available():
            pytest.skip("native workqueue unavailable (no g++?)")
        return NativeRateLimitingQueue(name="t", base_delay=0.001,
                                       max_delay=0.05)
    return RateLimitingQueue(
        rate_limiter=ItemExponentialFailureRateLimiter(0.001, 0.05), name="t")


def test_dedup_while_queued(q):
    q.add("a")
    q.add("a")
    q.add("b")
    assert len(q) == 2


def test_readd_while_processing_requeues_on_done(q):
    q.add("a")
    item, _ = q.get()
    assert item == "a"
    q.add("a")  # while processing -> deferred
    assert len(q) == 0
    q.done("a")
    assert len(q) == 1
    item2, _ = q.get()
    assert item2 == "a"


def test_add_after_delivers_later(q):
    q.add_after("x", 0.05)
    assert len(q) == 0
    item, shutdown = q.get(timeout=1.0)
    assert item == "x" and not shutdown


def test_shutdown_unblocks_getters(q):
    results = []

    def worker():
        item, shutdown = q.get()
        results.append((item, shutdown))

    t = threading.Thread(target=worker)
    t.start()
    time.sleep(0.05)
    q.shutdown()
    t.join(timeout=2)
    assert not t.is_alive()
    assert results == [(None, True)]


def test_get_timeout_returns_none(q):
    item, shutdown = q.get(timeout=0.01)
    assert item is None and not shutdown


def test_drain_before_shutdown_signal(q):
    q.add("a")
    q.shutdown()
    item, shutdown = q.get()
    assert item == "a" and not shutdown
    q.done("a")
    item, shutdown = q.get()
    assert shutdown


def test_no_delayed_delivery_after_shutdown(q):
    """Items still in backoff when shutdown() fires are never delivered
    (the waker exits in the Python queue; the native queue gates promotion
    on the shutdown flag)."""
    q.add_after("late", 0.02)
    q.shutdown()
    time.sleep(0.05)  # let the backoff elapse
    item, shutdown = q.get()
    assert item is None and shutdown


def test_rate_limited_requeues_and_forget(q):
    """One failure charge per scheduled delivery: requeues across
    dispatch cycles count; forget resets."""
    for _ in range(3):
        q.add_rate_limited("k")
        item, _ = q.get(timeout=1.0)
        assert item == "k"
        q.done("k")
    assert q.num_requeues("k") == 3
    q.forget("k")
    assert q.num_requeues("k") == 0


def test_rate_limited_deduped_adds_do_not_charge(q):
    """Adds that dedup into an existing pending delivery charge NO
    failure: healthy event traffic landing while a key waits out its
    backoff (or sits runnable) must not inflate the failure count —
    previously a busy key's backoff doubled per EVENT, parking its
    next delivery for minutes with zero real failures."""
    for _ in range(5):
        q.add_rate_limited("k")   # first schedules; rest dedup
    assert q.num_requeues("k") == 1
    item, _ = q.get(timeout=1.0)
    assert item == "k"
    q.done("k")
    # the deduped adds scheduled exactly one delivery
    item, shutdown = q.get(timeout=0.1)
    assert item is None and not shutdown


def test_rate_limited_item_delivered_after_backoff(q):
    q.add_rate_limited("k")  # first failure: ~base_delay
    item, shutdown = q.get(timeout=1.0)
    assert item == "k" and not shutdown


def test_concurrent_producers_consumers_no_loss_no_dup(q):
    """N producers × M consumers: every key processed, none twice
    concurrently (dirty/processing invariants under real thread contention —
    the property the reference gets from Go's race-free workqueue)."""
    n_keys = 200
    seen = {}
    lock = threading.Lock()

    def producer(base):
        for i in range(n_keys // 4):
            q.add(f"ns/{base}-{i}")

    def consumer():
        while True:
            item, shutdown = q.get()
            if shutdown:
                return
            with lock:
                seen[item] = seen.get(item, 0) + 1
            q.done(item)

    consumers = [threading.Thread(target=consumer) for _ in range(4)]
    for t in consumers:
        t.start()
    producers = [threading.Thread(target=producer, args=(b,))
                 for b in range(4)]
    for t in producers:
        t.start()
    for t in producers:
        t.join(timeout=5)
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        with lock:
            if len(seen) == n_keys:
                break
        time.sleep(0.01)
    q.shutdown()
    for t in consumers:
        t.join(timeout=5)
    assert len(seen) == n_keys
    # adds may legitimately coalesce, but nothing is lost
    assert all(c >= 1 for c in seen.values())


# -- priority tiers (ISSUE 7: overload resilience) --------------------------


@pytest.fixture(params=IMPLS)
def tq(request):
    """A tiered queue with a short aging horizon so starvation-bound
    tests run in milliseconds."""
    if request.param == "native":
        if not native_available():
            pytest.skip("native workqueue unavailable (no g++?)")
        return NativeRateLimitingQueue(name="tiers", base_delay=0.001,
                                       max_delay=0.05,
                                       aging_horizon=0.15)
    return RateLimitingQueue(
        rate_limiter=ItemExponentialFailureRateLimiter(0.001, 0.05),
        name="tiers", aging_horizon=0.15)


def drain_one(q, timeout=1.0):
    item, shutdown = q.get(timeout=timeout)
    assert item is not None and not shutdown
    meta = q.claimed_meta(item)
    q.done(item)
    return item, meta


def test_interactive_scheduled_ahead_of_background(tq):
    """A fresh interactive item beats earlier-enqueued background
    items (the resync wave must not delay a user-visible change)."""
    tq.add("ns/bg1", klass="background")
    tq.add("ns/bg2", klass="background")
    tq.add("ns/hot", klass="interactive")
    assert drain_one(tq)[0] == "ns/hot"
    assert drain_one(tq)[0] == "ns/bg1"
    assert drain_one(tq)[0] == "ns/bg2"


def test_aging_promotes_waiting_background_item(tq):
    """Aging promotion order: once a background item has waited past
    the horizon (plus the fresh interactive head's wait), it is served
    BEFORE further interactive items — the anti-starvation rule."""
    tq.add("ns/old-bg", klass="background")
    time.sleep(0.25)   # > aging_horizon
    tq.add("ns/fresh-i", klass="interactive")
    assert drain_one(tq)[0] == "ns/old-bg"
    assert drain_one(tq)[0] == "ns/fresh-i"


def test_class_preserved_across_done_and_rate_limited_requeue(tq):
    """done() -> add_rate_limited (a failed sync's requeue path) keeps
    the key's class: a background sweep retry stays background, an
    interactive retry stays interactive (CLASS_KEEP)."""
    tq.add("ns/bg", klass="background")
    item, shutdown = tq.get(timeout=1.0)
    assert item == "ns/bg"
    assert tq.claimed_meta("ns/bg")[0] == "background"
    tq.add_rate_limited("ns/bg")   # the reconcile requeue: keep class
    tq.done("ns/bg")
    item, _ = tq.get(timeout=1.0)
    assert item == "ns/bg"
    assert tq.claimed_meta("ns/bg")[0] == "background"
    tq.done("ns/bg")

    tq.add("ns/hot", klass="interactive")
    item, _ = tq.get(timeout=1.0)
    tq.add_rate_limited("ns/hot", klass="keep")
    tq.done("ns/hot")
    item, _ = tq.get(timeout=1.0)
    assert tq.claimed_meta("ns/hot")[0] == "interactive"
    tq.done("ns/hot")


def test_background_retag_does_not_demote_pending_interactive(tq):
    """Upgrade-only classing: a resync wave re-tagging a key whose
    interactive delivery is still pending must not demote it."""
    tq.add("ns/k", klass="interactive")
    tq.add("ns/k", klass="background")   # the wave's re-add (deduped)
    item, _ = tq.get(timeout=1.0)
    assert tq.claimed_meta("ns/k")[0] == "interactive"
    tq.done("ns/k")


def test_interactive_add_promotes_background_pending(tq):
    """An event landing on a key already waiting in the background
    tier promotes it: the user-visible change does not wait out the
    backlog it was enqueued behind."""
    tq.add("ns/bg1", klass="background")
    tq.add("ns/bg2", klass="background")
    tq.add("ns/bg2", klass="interactive")   # the watch event
    assert drain_one(tq)[0] == "ns/bg2"
    assert drain_one(tq)[0] == "ns/bg1"


def test_starvation_bound_under_saturating_interactive_storm(tq):
    """The anti-starvation acceptance bound: under a saturating
    interactive storm (fresh interactive items always pending), a
    background item is served within ~the aging horizon of enqueue,
    never parked indefinitely."""
    stop = threading.Event()
    served_bg = threading.Event()
    bg_enqueued = time.monotonic()
    tq.add("ns/parked", klass="background")

    def storm():
        i = 0
        while not stop.is_set():
            tq.add(f"ns/storm-{i}", klass="interactive")
            i += 1
            time.sleep(0.001)

    def consumer():
        while not stop.is_set():
            item, shutdown = tq.get(timeout=0.2)
            if shutdown or item is None:
                continue
            if item == "ns/parked":
                served_bg.set()
            tq.done(item)

    threads = [threading.Thread(target=storm),
               threading.Thread(target=consumer)]
    for t in threads:
        t.start()
    try:
        assert served_bg.wait(timeout=5.0), \
            "background item starved by the interactive storm"
        waited = time.monotonic() - bg_enqueued
        # horizon 0.15s + generous scheduling slack for loaded CI
        assert waited <= 1.5, \
            f"background item waited {waited:.2f}s (aging horizon 0.15s)"
    finally:
        stop.set()
        tq.shutdown()
        for t in threads:
            t.join(timeout=5)


def test_parked_retry_promotes_ahead_of_storm_backlog(tq):
    """A parked key's retry (delay-heap promotion) whose request
    predates the backlog enters at the HEAD of its tier: its wait is
    bounded by its backoff, not by how deep the storm behind it is."""
    tq.add_after("ns/parked", 0.05, klass="interactive")
    time.sleep(0.01)
    for i in range(50):
        tq.add(f"ns/storm-{i}", klass="interactive")
    time.sleep(0.08)   # the park elapses behind the backlog
    item, _ = tq.get(timeout=1.0)
    assert item == "ns/parked", \
        f"parked retry buried behind the storm (got {item})"
    tq.done(item)
    # and same-batch ordering stays FIFO for the storm itself
    item, _ = tq.get(timeout=1.0)
    assert item == "ns/storm-0"
    tq.done(item)


def test_shutdown_drains_all_tiers_exactly_once(tq):
    """Items pending in BOTH tiers at shutdown() are each delivered
    exactly once before get() reports shutdown."""
    tq.add("ns/i1", klass="interactive")
    tq.add("ns/b1", klass="background")
    tq.add("ns/i2", klass="interactive")
    tq.shutdown()
    seen = []
    while True:
        item, shutdown = tq.get(timeout=1.0)
        if shutdown:
            break
        seen.append(item)
        tq.done(item)
    assert sorted(seen) == ["ns/b1", "ns/i1", "ns/i2"]


def test_add_after_keeps_earliest_deadline(q):
    """Regression (ISSUE 7 satellite): two pending parks for one item
    — a long breaker hint then a shorter retry hint — must wake at the
    EARLIEST deadline, and the superseded later entry must not
    re-deliver the item afterwards."""
    q.add_after("ns/parked", 5.0)    # the breaker's long park
    q.add_after("ns/parked", 0.03)   # the shorter retry hint
    t0 = time.monotonic()
    item, shutdown = q.get(timeout=2.0)
    elapsed = time.monotonic() - t0
    assert item == "ns/parked" and not shutdown
    assert elapsed < 2.0, "item must wake on the earliest deadline"
    q.done("ns/parked")
    # the superseded 5s entry is dead: nothing re-delivers
    item, shutdown = q.get(timeout=0.1)
    assert item is None and not shutdown


def test_add_after_later_deadline_ignored_for_pending_item(q):
    """The mirror case: a LATER park for an already-pending item must
    not push the wake time out."""
    q.add_after("ns/parked", 0.03)
    q.add_after("ns/parked", 5.0)
    item, shutdown = q.get(timeout=2.0)
    assert item == "ns/parked" and not shutdown
    q.done("ns/parked")


def test_overload_signal_depth_and_age(tq):
    """overloaded() trips on the depth watermark, and on the oldest
    interactive item's age watermark."""
    if isinstance(tq, NativeRateLimitingQueue):
        tq.depth_watermark, tq.age_watermark = 3, 0.1
    else:
        tq.depth_watermark, tq.age_watermark = 3, 0.1
    assert tq.overloaded() is None
    for i in range(4):
        tq.add(f"ns/d{i}", klass="background")
    assert tq.overloaded() == "depth"
    for _ in range(4):
        item, _ = tq.get(timeout=1.0)
        tq.done(item)
    assert tq.overloaded() is None
    tq.add("ns/slow", klass="interactive")
    time.sleep(0.2)
    assert tq.overloaded() == "age"


def test_tier_len_and_oldest_age_observability(tq):
    """The per-tier depth/age accessors the gauges read."""
    assert tq.tier_len("interactive") == 0
    assert tq.tier_oldest_age("background") == 0.0
    tq.add("ns/a", klass="interactive")
    tq.add("ns/b", klass="background")
    tq.add("ns/c", klass="background")
    assert tq.tier_len("interactive") == 1
    assert tq.tier_len("background") == 2
    time.sleep(0.05)
    assert tq.tier_oldest_age("background") >= 0.04
    assert len(tq) == 3


# -- limiter unit tables (Python objects; native equivalents asserted via
#    the queue-level tests above) -------------------------------------------


def test_rate_limited_backoff_grows_and_forget_resets():
    rl = ItemExponentialFailureRateLimiter(0.001, 10.0)
    delays = [rl.when("k") for _ in range(4)]
    assert delays == [0.001, 0.002, 0.004, 0.008]
    assert rl.num_requeues("k") == 4
    rl.forget("k")
    assert rl.when("k") == 0.001


def test_bucket_rate_limiter_burst():
    b = BucketRateLimiter(qps=10.0, burst=2)
    assert b.when("a") == 0.0
    assert b.when("b") == 0.0
    assert b.when("c") > 0.0  # out of burst


# -- factory ---------------------------------------------------------------


def test_factory_forced_python(monkeypatch):
    monkeypatch.setenv("AGAC_NATIVE_WORKQUEUE", "0")
    assert isinstance(new_rate_limiting_queue(name="f"), RateLimitingQueue)


def test_factory_auto_prefers_native_when_available(monkeypatch):
    monkeypatch.delenv("AGAC_NATIVE_WORKQUEUE", raising=False)
    queue = new_rate_limiting_queue(name="f")
    if native_available():
        assert isinstance(queue, NativeRateLimitingQueue)
    else:
        assert isinstance(queue, RateLimitingQueue)


def test_native_backoff_sequence_matches_python():
    """The C++ exponential-backoff table must match the Python limiter."""
    if not native_available():
        pytest.skip("native workqueue unavailable")
    nq = NativeRateLimitingQueue(name="eq", base_delay=0.004, max_delay=0.02)
    rl = ItemExponentialFailureRateLimiter(0.004, 0.02)
    for expected in [rl.when("k") for _ in range(5)]:
        t0 = time.monotonic()
        nq.add_rate_limited("k")
        item, _ = nq.get(timeout=2.0)
        elapsed = time.monotonic() - t0
        assert item == "k"
        nq.done("k")
        # delivered no earlier than the scheduled backoff (with sched
        # slack); no tight upper bound — wall-clock stalls on loaded CI
        # runners would make it flaky
        assert elapsed >= expected - 0.002


# ---------------------------------------------------------------------------
# remove(): the per-shard queue-ownership purge (ISSUE 8)
# ---------------------------------------------------------------------------

def test_remove_pending_item_never_delivered(q):
    q.add("keep")
    q.add("purged")
    assert q.remove("purged") is True
    got = set()
    for _ in range(2):
        item, _ = q.get(timeout=0.2)
        if item is None:
            break
        got.add(item)
        q.done(item)
    assert got == {"keep"}
    assert q.remove("unknown") is False


def test_remove_parked_item_cancels_the_wake(q):
    q.add_after("parked", 0.05)
    assert q.remove("parked") is True
    time.sleep(0.15)
    item, _ = q.get(timeout=0.05)
    assert item is None, "a removed parked item was still delivered"


def test_remove_processing_item_cancels_requeue_only(q):
    q.add("held")
    item, _ = q.get(timeout=1.0)
    assert item == "held"
    q.add("held")                   # dirty while processing
    assert q.remove("held") is True  # cancels the pending re-delivery
    q.done(item)
    got, _ = q.get(timeout=0.1)
    assert got is None, "done() re-queued a removed item"


def test_remove_resets_limiter_state(q):
    for _ in range(6):
        q.add_rate_limited("flappy")
        item, _ = q.get(timeout=2.0)
        q.done(item)
    assert q.num_requeues("flappy") >= 1
    q.remove("flappy")
    assert q.num_requeues("flappy") == 0
