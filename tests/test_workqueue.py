"""Workqueue semantics tests (client-go invariants the controllers rely on)."""
import threading
import time

from aws_global_accelerator_controller_tpu.kube.workqueue import (
    BucketRateLimiter,
    ItemExponentialFailureRateLimiter,
    RateLimitingQueue,
)


def make_queue():
    # fast limiter so tests don't sleep long
    return RateLimitingQueue(
        rate_limiter=ItemExponentialFailureRateLimiter(0.001, 0.05), name="t")


def test_dedup_while_queued():
    q = make_queue()
    q.add("a")
    q.add("a")
    q.add("b")
    assert len(q) == 2


def test_readd_while_processing_requeues_on_done():
    q = make_queue()
    q.add("a")
    item, _ = q.get()
    assert item == "a"
    q.add("a")  # while processing -> deferred
    assert len(q) == 0
    q.done("a")
    assert len(q) == 1
    item2, _ = q.get()
    assert item2 == "a"


def test_add_after_delivers_later():
    q = make_queue()
    q.add_after("x", 0.05)
    assert len(q) == 0
    item, shutdown = q.get(timeout=1.0)
    assert item == "x" and not shutdown


def test_rate_limited_backoff_grows_and_forget_resets():
    rl = ItemExponentialFailureRateLimiter(0.001, 10.0)
    delays = [rl.when("k") for _ in range(4)]
    assert delays == [0.001, 0.002, 0.004, 0.008]
    assert rl.num_requeues("k") == 4
    rl.forget("k")
    assert rl.when("k") == 0.001


def test_bucket_rate_limiter_burst():
    b = BucketRateLimiter(qps=10.0, burst=2)
    assert b.when("a") == 0.0
    assert b.when("b") == 0.0
    assert b.when("c") > 0.0  # out of burst


def test_shutdown_unblocks_getters():
    q = make_queue()
    results = []

    def worker():
        item, shutdown = q.get()
        results.append((item, shutdown))

    t = threading.Thread(target=worker)
    t.start()
    time.sleep(0.05)
    q.shutdown()
    t.join(timeout=2)
    assert not t.is_alive()
    assert results == [(None, True)]


def test_get_timeout_returns_none():
    q = make_queue()
    item, shutdown = q.get(timeout=0.01)
    assert item is None and not shutdown


def test_drain_before_shutdown_signal():
    q = make_queue()
    q.add("a")
    q.shutdown()
    item, shutdown = q.get()
    assert item == "a" and not shutdown
    q.done("a")
    item, shutdown = q.get()
    assert shutdown
