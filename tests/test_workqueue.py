"""Workqueue semantics tests (client-go invariants the controllers rely on).

Every queue-level test runs against BOTH implementations — the pure-Python
RateLimitingQueue and the native C++ one (native/workqueue.cpp via ctypes)
— since new_rate_limiting_queue may hand controllers either.
"""
import threading
import time

import pytest

from aws_global_accelerator_controller_tpu.kube.workqueue import (
    BucketRateLimiter,
    ItemExponentialFailureRateLimiter,
    RateLimitingQueue,
    new_rate_limiting_queue,
)
from aws_global_accelerator_controller_tpu.kube.native_workqueue import (
    NativeRateLimitingQueue,
    native_available,
)

IMPLS = ["python", "native"]


@pytest.fixture(params=IMPLS)
def q(request):
    """A queue with a fast limiter so tests don't sleep long."""
    if request.param == "native":
        if not native_available():
            pytest.skip("native workqueue unavailable (no g++?)")
        return NativeRateLimitingQueue(name="t", base_delay=0.001,
                                       max_delay=0.05)
    return RateLimitingQueue(
        rate_limiter=ItemExponentialFailureRateLimiter(0.001, 0.05), name="t")


def test_dedup_while_queued(q):
    q.add("a")
    q.add("a")
    q.add("b")
    assert len(q) == 2


def test_readd_while_processing_requeues_on_done(q):
    q.add("a")
    item, _ = q.get()
    assert item == "a"
    q.add("a")  # while processing -> deferred
    assert len(q) == 0
    q.done("a")
    assert len(q) == 1
    item2, _ = q.get()
    assert item2 == "a"


def test_add_after_delivers_later(q):
    q.add_after("x", 0.05)
    assert len(q) == 0
    item, shutdown = q.get(timeout=1.0)
    assert item == "x" and not shutdown


def test_shutdown_unblocks_getters(q):
    results = []

    def worker():
        item, shutdown = q.get()
        results.append((item, shutdown))

    t = threading.Thread(target=worker)
    t.start()
    time.sleep(0.05)
    q.shutdown()
    t.join(timeout=2)
    assert not t.is_alive()
    assert results == [(None, True)]


def test_get_timeout_returns_none(q):
    item, shutdown = q.get(timeout=0.01)
    assert item is None and not shutdown


def test_drain_before_shutdown_signal(q):
    q.add("a")
    q.shutdown()
    item, shutdown = q.get()
    assert item == "a" and not shutdown
    q.done("a")
    item, shutdown = q.get()
    assert shutdown


def test_no_delayed_delivery_after_shutdown(q):
    """Items still in backoff when shutdown() fires are never delivered
    (the waker exits in the Python queue; the native queue gates promotion
    on the shutdown flag)."""
    q.add_after("late", 0.02)
    q.shutdown()
    time.sleep(0.05)  # let the backoff elapse
    item, shutdown = q.get()
    assert item is None and shutdown


def test_rate_limited_requeues_and_forget(q):
    for _ in range(3):
        q.add_rate_limited("k")
    assert q.num_requeues("k") == 3
    q.forget("k")
    assert q.num_requeues("k") == 0


def test_rate_limited_item_delivered_after_backoff(q):
    q.add_rate_limited("k")  # first failure: ~base_delay
    item, shutdown = q.get(timeout=1.0)
    assert item == "k" and not shutdown


def test_concurrent_producers_consumers_no_loss_no_dup(q):
    """N producers × M consumers: every key processed, none twice
    concurrently (dirty/processing invariants under real thread contention —
    the property the reference gets from Go's race-free workqueue)."""
    n_keys = 200
    seen = {}
    lock = threading.Lock()

    def producer(base):
        for i in range(n_keys // 4):
            q.add(f"ns/{base}-{i}")

    def consumer():
        while True:
            item, shutdown = q.get()
            if shutdown:
                return
            with lock:
                seen[item] = seen.get(item, 0) + 1
            q.done(item)

    consumers = [threading.Thread(target=consumer) for _ in range(4)]
    for t in consumers:
        t.start()
    producers = [threading.Thread(target=producer, args=(b,))
                 for b in range(4)]
    for t in producers:
        t.start()
    for t in producers:
        t.join(timeout=5)
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        with lock:
            if len(seen) == n_keys:
                break
        time.sleep(0.01)
    q.shutdown()
    for t in consumers:
        t.join(timeout=5)
    assert len(seen) == n_keys
    # adds may legitimately coalesce, but nothing is lost
    assert all(c >= 1 for c in seen.values())


# -- limiter unit tables (Python objects; native equivalents asserted via
#    the queue-level tests above) -------------------------------------------


def test_rate_limited_backoff_grows_and_forget_resets():
    rl = ItemExponentialFailureRateLimiter(0.001, 10.0)
    delays = [rl.when("k") for _ in range(4)]
    assert delays == [0.001, 0.002, 0.004, 0.008]
    assert rl.num_requeues("k") == 4
    rl.forget("k")
    assert rl.when("k") == 0.001


def test_bucket_rate_limiter_burst():
    b = BucketRateLimiter(qps=10.0, burst=2)
    assert b.when("a") == 0.0
    assert b.when("b") == 0.0
    assert b.when("c") > 0.0  # out of burst


# -- factory ---------------------------------------------------------------


def test_factory_forced_python(monkeypatch):
    monkeypatch.setenv("AGAC_NATIVE_WORKQUEUE", "0")
    assert isinstance(new_rate_limiting_queue(name="f"), RateLimitingQueue)


def test_factory_auto_prefers_native_when_available(monkeypatch):
    monkeypatch.delenv("AGAC_NATIVE_WORKQUEUE", raising=False)
    queue = new_rate_limiting_queue(name="f")
    if native_available():
        assert isinstance(queue, NativeRateLimitingQueue)
    else:
        assert isinstance(queue, RateLimitingQueue)


def test_native_backoff_sequence_matches_python():
    """The C++ exponential-backoff table must match the Python limiter."""
    if not native_available():
        pytest.skip("native workqueue unavailable")
    nq = NativeRateLimitingQueue(name="eq", base_delay=0.004, max_delay=0.02)
    rl = ItemExponentialFailureRateLimiter(0.004, 0.02)
    for expected in [rl.when("k") for _ in range(5)]:
        t0 = time.monotonic()
        nq.add_rate_limited("k")
        item, _ = nq.get(timeout=2.0)
        elapsed = time.monotonic() - t0
        assert item == "k"
        nq.done("k")
        # delivered no earlier than the scheduled backoff (with sched
        # slack); no tight upper bound — wall-clock stalls on loaded CI
        # runners would make it flaky
        assert elapsed >= expected - 0.002
