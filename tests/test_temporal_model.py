"""Temporal traffic model: flash/reference consistency, training signal,
weight-plan validity."""
import jax
import jax.numpy as jnp
import numpy as np

from aws_global_accelerator_controller_tpu.models.temporal import (
    TemporalTrafficModel,
    synthetic_window,
)


def _setup(attention="flash", seed=0):
    model = TemporalTrafficModel(feature_dim=8, embed_dim=16,
                                 hidden_dim=32, attention=attention)
    params = model.init_params(jax.random.PRNGKey(seed))
    window, batch = synthetic_window(jax.random.PRNGKey(seed + 1),
                                     steps=8, groups=4, endpoints=8)
    return model, params, window, batch


def test_flash_and_reference_scores_agree():
    """Serving (flash) and training (reference) attention paths must
    produce the same scores, or train/serve skew corrupts plans.  The
    window must be >= FLASH_MIN_WINDOW or serving also takes the dense
    path and the comparison is vacuous."""
    from aws_global_accelerator_controller_tpu.models.temporal import (
        FLASH_MIN_WINDOW,
    )

    model = TemporalTrafficModel(feature_dim=8, embed_dim=16,
                                 hidden_dim=32, attention="flash_always")
    ref_model = TemporalTrafficModel(feature_dim=8, embed_dim=16,
                                     hidden_dim=32, attention="reference")
    params = model.init_params(jax.random.PRNGKey(0))
    window, _ = synthetic_window(jax.random.PRNGKey(1),
                                 steps=FLASH_MIN_WINDOW, groups=2,
                                 endpoints=4)
    flash = model.scores(params, window)
    ref = ref_model.scores(params, window)
    np.testing.assert_allclose(np.asarray(flash), np.asarray(ref),
                               rtol=2e-2, atol=2e-2)  # bf16 matmuls


def test_short_windows_route_to_dense_reference(monkeypatch):
    """Below FLASH_MIN_WINDOW the serving path must not invoke the
    Pallas kernel at all (dispatch overhead beats it)."""
    import aws_global_accelerator_controller_tpu.ops.pallas_attention as pa

    def boom(*a, **k):  # pragma: no cover - would fail the test
        raise AssertionError("flash kernel called for a short window")

    monkeypatch.setattr(pa, "flash_attention", boom)
    model, params, window, batch = _setup(attention="flash_always")
    weights = model.forward(params, window, batch.mask)  # steps=8 < 64
    assert weights.shape == (4, 8)


def test_flash_auto_gates_on_backend(monkeypatch):
    """attention='flash' must not run interpret-mode pallas off-TPU —
    the dense reference is the off-TPU serving path."""
    import aws_global_accelerator_controller_tpu.ops.pallas_attention as pa
    from aws_global_accelerator_controller_tpu.models.temporal import (
        FLASH_MIN_WINDOW,
    )

    def boom(*a, **k):  # pragma: no cover - would fail the test
        raise AssertionError("flash kernel called off-TPU")

    monkeypatch.setattr(pa, "flash_attention", boom)
    model = TemporalTrafficModel(feature_dim=8, embed_dim=16,
                                 hidden_dim=32, attention="flash")
    params = model.init_params(jax.random.PRNGKey(0))
    window, batch = synthetic_window(jax.random.PRNGKey(1),
                                     steps=FLASH_MIN_WINDOW, groups=2,
                                     endpoints=4)
    assert jax.default_backend() != "tpu"  # conftest pins cpu
    model.forward(params, window, batch.mask)


def test_train_step_executes_flash_kernel_under_gradient(monkeypatch):
    """VERDICT r1 item 4: for windows >= FLASH_MIN_WINDOW the training
    step must run the Pallas kernel (via its custom VJP), not the dense
    fallback — and still learn.  The kernel-bearing regime is sequence
    supervision: with supervision="last" training deliberately takes
    the O(T) last-query path (the [T, T] attention's other rows have
    exactly zero gradient under that loss), so the kernel guarantee is
    asserted where the full attention is genuinely needed."""
    import aws_global_accelerator_controller_tpu.ops.pallas_attention as pa
    from aws_global_accelerator_controller_tpu.models.temporal import (
        FLASH_MIN_WINDOW,
    )

    calls = {"n": 0}
    real = pa.flash_attention

    def spy(*a, **k):
        calls["n"] += 1
        return real(*a, **k)

    monkeypatch.setattr(pa, "flash_attention", spy)
    model = TemporalTrafficModel(feature_dim=8, embed_dim=16,
                                 hidden_dim=32, attention="flash_always",
                                 supervision="sequence")
    params = model.init_params(jax.random.PRNGKey(2))
    window, batch = synthetic_window(jax.random.PRNGKey(3),
                                     steps=FLASH_MIN_WINDOW, groups=2,
                                     endpoints=4, per_step=True)
    opt = model.init_opt_state(params)
    params2, opt, loss = model.train_step(params, opt, window, batch)
    assert calls["n"] >= 1, "train_step never reached the flash kernel"
    assert np.isfinite(float(loss))
    # the kernel's VJP produced real gradients: params moved
    moved = jax.tree_util.tree_map(
        lambda a, b: float(jnp.abs(a.astype(jnp.float32)
                                   - b.astype(jnp.float32)).max()),
        params, params2)
    assert max(jax.tree_util.tree_leaves(moved)) > 0


def test_flash_and_reference_gradients_agree():
    """The flash VJP and the dense autodiff path must produce the same
    parameter gradients (bf16 tolerance) — otherwise training with the
    kernel silently optimises a different function."""
    from aws_global_accelerator_controller_tpu.models.temporal import (
        FLASH_MIN_WINDOW,
    )

    kwargs = dict(feature_dim=8, embed_dim=16, hidden_dim=32)
    flash_model = TemporalTrafficModel(attention="flash_always", **kwargs)
    ref_model = TemporalTrafficModel(attention="reference", **kwargs)
    params = flash_model.init_params(jax.random.PRNGKey(4))
    window, batch = synthetic_window(jax.random.PRNGKey(5),
                                     steps=FLASH_MIN_WINDOW, groups=2,
                                     endpoints=4)
    g_flash = jax.grad(flash_model.loss)(params, window, batch)
    g_ref = jax.grad(ref_model.loss)(params, window, batch)
    for name in params:
        np.testing.assert_allclose(
            np.asarray(g_flash[name], dtype=np.float32),
            np.asarray(g_ref[name], dtype=np.float32),
            rtol=5e-2, atol=5e-3, err_msg=f"grad[{name}]")


def test_forward_emits_valid_weights():
    model, params, window, batch = _setup()
    weights = jax.jit(model.forward)(params, window, batch.mask)
    w = np.asarray(weights)
    assert w.shape == (4, 8)
    assert ((w >= 0) & (w <= 255)).all()
    assert (w[~np.asarray(batch.mask)] == 0).all()


def test_training_reduces_loss():
    model, params, window, batch = _setup(seed=3)
    opt = model.init_opt_state(params)
    step = jax.jit(model.train_step)
    first = None
    for i in range(30):
        params, opt, loss = step(params, opt, window, batch)
        if first is None:
            first = float(loss)
    assert float(loss) < first


def test_scores_use_history_not_just_last_step():
    """Perturbing an early timestep must change the scores — the whole
    point of the temporal model vs the snapshot MLP."""
    model, params, window, _ = _setup(seed=5)
    base = model.scores(params, window)
    w2 = window.at[0].add(2.0)
    got = model.scores(params, w2)
    assert not np.allclose(np.asarray(base), np.asarray(got))


def test_unknown_attention_impl_rejected():
    import pytest

    with pytest.raises(ValueError):
        TemporalTrafficModel(attention="nope")


# -- O(T) last-query serving path + sequence supervision --------------------


def test_scores_last_matches_full_attention():
    """The O(T) last-query path computes the same scores as the full
    causal attention's final row (float-association tolerance).  Both
    paths project q/k/v through the SAME composed [F, *] matrices
    (x @ (We@W..) — _embed_qkv/_embed_kv docstrings), so their
    projections agree bitwise per column and the only daylight is the
    attention reduction order; observed gap at this seed is exactly
    0.0."""
    model = TemporalTrafficModel(feature_dim=8, embed_dim=16,
                                 hidden_dim=32, attention="reference")
    params = model.init_params(jax.random.PRNGKey(0))
    window, _ = synthetic_window(jax.random.PRNGKey(1), steps=32,
                                 groups=4, endpoints=8)
    full = np.asarray(model.scores(params, window))
    fast = np.asarray(model.scores_last(params, window))
    np.testing.assert_allclose(fast, full, rtol=1e-4, atol=1e-5)


def test_attention_last_reference_equals_oracle_last_row():
    from aws_global_accelerator_controller_tpu.models.temporal import (
        attention_last_reference,
    )
    from aws_global_accelerator_controller_tpu.parallel.ring_attention import (  # noqa: E501
        attention_reference,
    )

    ks = jax.random.split(jax.random.PRNGKey(5), 3)
    q, k, v = (jax.random.normal(kk, (24, 6, 16), jnp.bfloat16)
               for kk in ks)
    want = np.asarray(attention_reference(q, k, v, causal=True)[-1])
    got = np.asarray(attention_last_reference(q[-1], k, v))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_last_supervision_training_reduces_loss():
    model = TemporalTrafficModel(feature_dim=8, embed_dim=16,
                                 hidden_dim=32)
    params = model.init_params(jax.random.PRNGKey(0))
    window, batch = synthetic_window(jax.random.PRNGKey(1), steps=16,
                                     groups=4, endpoints=8)
    opt = model.init_opt_state(params)
    first = float(model.loss(params, window, batch))
    step = jax.jit(model.train_step)
    for _ in range(30):
        params, opt, loss = step(params, opt, window, batch)
    assert float(loss) < first


def test_sequence_supervision_training_reduces_loss():
    model = TemporalTrafficModel(feature_dim=8, embed_dim=16,
                                 hidden_dim=32, attention="reference",
                                 supervision="sequence")
    params = model.init_params(jax.random.PRNGKey(0))
    window, batch = synthetic_window(jax.random.PRNGKey(1), steps=16,
                                     groups=4, endpoints=8,
                                     per_step=True)
    assert batch.target.shape == (16, 4, 8)
    opt = model.init_opt_state(params)
    first = float(model.loss(params, window, batch))
    step = jax.jit(model.train_step)
    for _ in range(30):
        params, opt, loss = step(params, opt, window, batch)
    assert float(loss) < first


def test_forward_serving_uses_last_query_path(monkeypatch):
    """Serving must not pay for the [T, T] attention: forward() with no
    attend override never calls the full-attention scorers."""
    model = TemporalTrafficModel(feature_dim=8, embed_dim=16,
                                 hidden_dim=32,
                                 attention="flash_always")
    called = {"full": 0}
    orig = model._attend

    def spy(*a, **k):
        called["full"] += 1
        return orig(*a, **k)

    monkeypatch.setattr(model, "_attend", spy)
    params = model.init_params(jax.random.PRNGKey(0))
    window, batch = synthetic_window(jax.random.PRNGKey(1), steps=128,
                                     groups=2, endpoints=4)
    w = np.asarray(model.forward(params, window, batch.mask))
    assert called["full"] == 0
    assert (w >= 0).all() and (w <= 255).all()


def test_unknown_supervision_rejected():
    import pytest

    with pytest.raises(ValueError, match="supervision"):
        TemporalTrafficModel(supervision="middle")


def test_sequence_remat_identical_trajectory():
    """jax.checkpoint around the per-step head replays the same f32
    ops, so remat training is numerically identical — only cheaper in
    activation memory (the deep family's remat law)."""
    kw = dict(feature_dim=8, embed_dim=16, hidden_dim=32,
              attention="reference", supervision="sequence")
    plain = TemporalTrafficModel(**kw)
    remat = TemporalTrafficModel(remat=True, **kw)
    params = plain.init_params(jax.random.PRNGKey(0))
    window, batch = synthetic_window(jax.random.PRNGKey(1), steps=16,
                                     groups=4, endpoints=8,
                                     per_step=True)
    p1, o1 = dict(params), plain.init_opt_state(params)
    p2, o2 = dict(params), remat.init_opt_state(params)
    s1 = jax.jit(plain.train_step)
    s2 = jax.jit(remat.train_step)
    for _ in range(3):
        p1, o1, l1 = s1(p1, o1, window, batch)
        p2, o2, l2 = s2(p2, o2, window, batch)
        assert float(l1) == float(l2)
    for k in p1:
        np.testing.assert_array_equal(np.asarray(p1[k]),
                                      np.asarray(p2[k]), err_msg=k)


def test_attention_chunk_exact():
    """Splitting the streams axis into head chunks is exact: attention
    is per-head independent, so chunked == unchunked (forward AND the
    training gradient), and a chunk >= S is a no-op split."""
    import jax
    import jax.numpy as jnp

    from aws_global_accelerator_controller_tpu.models.temporal import (
        TemporalTrafficModel,
        synthetic_window,
    )

    kwargs = dict(feature_dim=8, embed_dim=32, hidden_dim=64,
                  attention="flash_always", supervision="sequence")
    whole = TemporalTrafficModel(**kwargs)
    chunked = TemporalTrafficModel(attention_chunk=3, **kwargs)  # ragged
    wide = TemporalTrafficModel(attention_chunk=64, **kwargs)    # no-op
    window, batch = synthetic_window(jax.random.PRNGKey(0), steps=64,
                                     groups=2, endpoints=4,
                                     per_step=True)
    params = whole.init_params(jax.random.PRNGKey(1))
    sw = whole.scores_seq(params, window)
    sc = chunked.scores_seq(params, window)
    sn = wide.scores_seq(params, window)
    assert jnp.allclose(sw, sc, rtol=1e-5, atol=1e-5)
    assert jnp.allclose(sw, sn, rtol=1e-5, atol=1e-5)

    gw = jax.grad(lambda p: whole.loss(p, window, batch))(params)
    gc = jax.grad(lambda p: chunked.loss(p, window, batch))(params)
    for name in gw:
        a = gw[name].astype(jnp.float32)
        b = gc[name].astype(jnp.float32)
        assert jnp.allclose(a, b, rtol=2e-2, atol=2e-2), name


def test_attention_chunk_validation():
    import pytest

    from aws_global_accelerator_controller_tpu.models.temporal import (
        TemporalTrafficModel,
    )

    with pytest.raises(ValueError):
        TemporalTrafficModel(attention_chunk=-1)
