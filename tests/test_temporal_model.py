"""Temporal traffic model: flash/reference consistency, training signal,
weight-plan validity."""
import jax
import jax.numpy as jnp
import numpy as np

from aws_global_accelerator_controller_tpu.models.temporal import (
    TemporalTrafficModel,
    synthetic_window,
)


def _setup(attention="flash", seed=0):
    model = TemporalTrafficModel(feature_dim=8, embed_dim=16,
                                 hidden_dim=32, attention=attention)
    params = model.init_params(jax.random.PRNGKey(seed))
    window, batch = synthetic_window(jax.random.PRNGKey(seed + 1),
                                     steps=8, groups=4, endpoints=8)
    return model, params, window, batch


def test_flash_and_reference_scores_agree():
    """Serving (flash) and training (reference) attention paths must
    produce the same scores, or train/serve skew corrupts plans.  The
    window must be >= FLASH_MIN_WINDOW or serving also takes the dense
    path and the comparison is vacuous."""
    from aws_global_accelerator_controller_tpu.models.temporal import (
        FLASH_MIN_WINDOW,
    )

    model = TemporalTrafficModel(feature_dim=8, embed_dim=16,
                                 hidden_dim=32, attention="flash")
    params = model.init_params(jax.random.PRNGKey(0))
    window, _ = synthetic_window(jax.random.PRNGKey(1),
                                 steps=FLASH_MIN_WINDOW, groups=2,
                                 endpoints=4)
    flash = model.scores(params, window)
    ref = model.scores(params, window, differentiable=True)
    np.testing.assert_allclose(np.asarray(flash), np.asarray(ref),
                               rtol=2e-2, atol=2e-2)  # bf16 matmuls


def test_short_windows_route_to_dense_reference(monkeypatch):
    """Below FLASH_MIN_WINDOW the serving path must not invoke the
    Pallas kernel at all (padding waste)."""
    import aws_global_accelerator_controller_tpu.ops.pallas_attention as pa

    def boom(*a, **k):  # pragma: no cover - would fail the test
        raise AssertionError("flash kernel called for a short window")

    monkeypatch.setattr(pa, "flash_attention", boom)
    model, params, window, batch = _setup()  # steps=8 < 64
    weights = model.forward(params, window, batch.mask)
    assert weights.shape == (4, 8)


def test_forward_emits_valid_weights():
    model, params, window, batch = _setup()
    weights = jax.jit(model.forward)(params, window, batch.mask)
    w = np.asarray(weights)
    assert w.shape == (4, 8)
    assert ((w >= 0) & (w <= 255)).all()
    assert (w[~np.asarray(batch.mask)] == 0).all()


def test_training_reduces_loss():
    model, params, window, batch = _setup(seed=3)
    opt = model.init_opt_state(params)
    step = jax.jit(model.train_step)
    first = None
    for i in range(30):
        params, opt, loss = step(params, opt, window, batch)
        if first is None:
            first = float(loss)
    assert float(loss) < first


def test_scores_use_history_not_just_last_step():
    """Perturbing an early timestep must change the scores — the whole
    point of the temporal model vs the snapshot MLP."""
    model, params, window, _ = _setup(seed=5)
    base = model.scores(params, window)
    w2 = window.at[0].add(2.0)
    got = model.scores(params, w2)
    assert not np.allclose(np.asarray(base), np.asarray(got))


def test_unknown_attention_impl_rejected():
    import pytest

    with pytest.raises(ValueError):
        TemporalTrafficModel(attention="nope")
