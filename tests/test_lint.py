"""hack/lint.py is the tree's lint gate (VERDICT r2 item 7: a real
linter, not compileall) — its rules must fire on bad code and stay
silent on the idioms this codebase actually uses, or the gate is
either porous or noise."""
import os
import subprocess
import sys
import textwrap

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "hack"))

import lint  # noqa: E402


def _findings(tmp_path, source):
    f = tmp_path / "case.py"
    f.write_text(textwrap.dedent(source))
    return [(x.code, x.line) for x in lint.lint_file(f)]


def test_unused_import_flagged(tmp_path):
    got = _findings(tmp_path, """\
        import os
        import sys

        print(sys.argv)
        """)
    assert got == [("L001", 1)]


def test_future_and_underscore_and_local_imports_exempt(tmp_path):
    got = _findings(tmp_path, """\
        from __future__ import annotations
        import os as _os

        def f():
            import json  # lazy-init pattern: function-local pass
            return 1
        """)
    assert got == []


def test_string_annotation_counts_as_use(tmp_path):
    got = _findings(tmp_path, """\
        from typing import Optional

        def f(x: "Optional[int]"):
            return x
        """)
    assert got == []


def test_all_export_counts_as_use(tmp_path):
    got = _findings(tmp_path, """\
        from m import thing

        __all__ = ["thing"]
        """)
    assert got == []


def test_unused_local_flagged_but_unpacking_exempt(tmp_path):
    got = _findings(tmp_path, """\
        def f():
            dead = compute()
            a, b = pair()
            return b
        """)
    assert got == [("L002", 2)]


def test_class_attribute_in_function_exempt(tmp_path):
    got = _findings(tmp_path, """\
        def f():
            class C:
                kind = "x"
            return C()
        """)
    assert got == []


def test_bare_except_and_mutable_default(tmp_path):
    got = _findings(tmp_path, """\
        def f(xs=[]):
            try:
                pass
            except:
                pass
        """)
    assert sorted(got) == [("L003", 4), ("L004", 1)]


def test_fstring_rules(tmp_path):
    got = _findings(tmp_path, """\
        def f(x):
            a = f"no placeholder"
            b = f"{x:>8}"
            return a, b
        """)
    assert got == [("L005", 2)]


def test_redefinition_flagged_but_decorated_exempt(tmp_path):
    got = _findings(tmp_path, """\
        class C:
            def f(self):
                return 1

            def f(self):
                return 2

            @property
            def g(self):
                return 1

            @g.setter
            def g(self, v):
                self._v = v
        """)
    assert got == [("L006", 5)]


def test_noqa_suppression_both_spellings(tmp_path):
    got = _findings(tmp_path, """\
        import os  # noqa
        import sys  # noqa: L001
        import json  # noqa: F401
        """)
    assert got == []


def test_tree_is_lint_clean():
    """The gate itself: the shipped tree carries zero findings (CI runs
    make lint; this keeps local pytest equivalent)."""
    proc = subprocess.run([sys.executable,
                           os.path.join("hack", "lint.py")],
                          capture_output=True, text=True,
                          cwd=os.path.dirname(os.path.dirname(
                              os.path.abspath(__file__))))
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_augassign_counts_as_use(tmp_path):
    got = _findings(tmp_path, """\
        def f(ref):
            buf = ref.buffer
            buf += [1]
        """)
    assert got == []


def test_nested_function_local_reported_once(tmp_path):
    got = _findings(tmp_path, """\
        def outer():
            def inner():
                dead = 1
            return inner
        """)
    assert got == [("L002", 3)]


def test_cli_rejects_missing_path(tmp_path):
    proc = subprocess.run(
        [sys.executable, os.path.join("hack", "lint.py"),
         str(tmp_path / "nope")],
        capture_output=True, text=True,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert proc.returncode == 2
    assert "no such file" in proc.stderr
