"""hack/lint.py is the tree's lint gate (VERDICT r2 item 7: a real
linter, not compileall) — its rules must fire on bad code and stay
silent on the idioms this codebase actually uses, or the gate is
either porous or noise."""
import os
import re
import subprocess
import sys
import textwrap

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "hack"))

import lint  # noqa: E402
import probe  # noqa: E402

ROOT_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _findings(tmp_path, source):
    f = tmp_path / "case.py"
    f.write_text(textwrap.dedent(source))
    return [(x.code, x.line) for x in lint.lint_file(f)]


def test_unused_import_flagged(tmp_path):
    got = _findings(tmp_path, """\
        import os
        import sys

        print(sys.argv)
        """)
    assert got == [("L001", 1)]


def test_future_and_underscore_and_local_imports_exempt(tmp_path):
    got = _findings(tmp_path, """\
        from __future__ import annotations
        import os as _os

        def f():
            import json  # lazy-init pattern: function-local pass
            return 1
        """)
    assert got == []


def test_string_annotation_counts_as_use(tmp_path):
    got = _findings(tmp_path, """\
        from typing import Optional

        def f(x: "Optional[int]"):
            return x
        """)
    assert got == []


def test_all_export_counts_as_use(tmp_path):
    got = _findings(tmp_path, """\
        from m import thing

        __all__ = ["thing"]
        """)
    assert got == []


def test_unused_local_flagged_but_unpacking_exempt(tmp_path):
    got = _findings(tmp_path, """\
        def f():
            dead = compute()
            a, b = pair()
            return b
        """)
    assert got == [("L002", 2)]


def test_class_attribute_in_function_exempt(tmp_path):
    got = _findings(tmp_path, """\
        def f():
            class C:
                kind = "x"
            return C()
        """)
    assert got == []


def test_bare_except_and_mutable_default(tmp_path):
    got = _findings(tmp_path, """\
        def f(xs=[]):
            try:
                pass
            except:
                pass
        """)
    assert sorted(got) == [("L003", 4), ("L004", 1)]


def test_fstring_rules(tmp_path):
    got = _findings(tmp_path, """\
        def f(x):
            a = f"no placeholder"
            b = f"{x:>8}"
            return a, b
        """)
    assert got == [("L005", 2)]


def test_redefinition_flagged_but_decorated_exempt(tmp_path):
    got = _findings(tmp_path, """\
        class C:
            def f(self):
                return 1

            def f(self):
                return 2

            @property
            def g(self):
                return 1

            @g.setter
            def g(self, v):
                self._v = v
        """)
    assert got == [("L006", 5)]


def test_noqa_suppression_both_spellings(tmp_path):
    got = _findings(tmp_path, """\
        import os  # noqa
        import sys  # noqa: L001
        import json  # noqa: F401
        """)
    assert got == []


def test_tree_is_lint_clean():
    """The gate itself: the shipped tree carries zero findings across
    BOTH passes — base rules and the L1xx concurrency contracts (CI
    runs make lint; this keeps local pytest equivalent)."""
    proc = subprocess.run([sys.executable,
                           os.path.join("hack", "lint.py"), "--all"],
                          capture_output=True, text=True,
                          cwd=os.path.dirname(os.path.dirname(
                              os.path.abspath(__file__))))
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_augassign_counts_as_use(tmp_path):
    got = _findings(tmp_path, """\
        def f(ref):
            buf = ref.buffer
            buf += [1]
        """)
    assert got == []


def test_nested_function_local_reported_once(tmp_path):
    got = _findings(tmp_path, """\
        def outer():
            def inner():
                dead = 1
            return inner
        """)
    assert got == [("L002", 3)]


def test_cli_rejects_missing_path(tmp_path):
    proc = subprocess.run(
        [sys.executable, os.path.join("hack", "lint.py"),
         str(tmp_path / "nope")],
        capture_output=True, text=True,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert proc.returncode == 2
    assert "no such file" in proc.stderr


# -- L007: useless noqa ------------------------------------------------

def test_useless_noqa_flagged(tmp_path):
    got = _findings(tmp_path, """\
        import os  # noqa: F401

        print(os.path)
        """)
    assert got == [("L007", 1)]


def test_useful_noqa_not_flagged(tmp_path):
    got = _findings(tmp_path, """\
        import os  # noqa: F401
        """)
    assert got == []


def test_unknown_linter_codes_left_alone(tmp_path):
    """E402/E501-class codes belong to linters this suite does not
    implement — L007 must not demand their deletion."""
    got = _findings(tmp_path, """\
        import os  # noqa: E402

        print(os.path)
        """)
    assert got == []


def test_noqa_inside_string_constant_ignored(tmp_path):
    got = _findings(tmp_path, """\
        SNIPPET = '''
        import sys  # noqa: L001
        '''
        print(SNIPPET)
        """)
    assert got == []


# -- concurrency rules (L101-L104) -------------------------------------

import pathlib  # noqa: E402

from aws_global_accelerator_controller_tpu.analysis import (  # noqa: E402
    concurrency_lint,
)

FIXTURES = pathlib.Path(os.path.dirname(os.path.abspath(__file__))) \
    / "lint_fixtures"


def _cfindings(name):
    findings = concurrency_lint.lint_files([FIXTURES / name])
    return [(f.code, f.line) for f in findings]


def test_l101_ordering_inversion_fires():
    assert _cfindings("l101_inversion.py") == [("L101", 10)]


def test_l101_same_lock_nested_fires():
    assert _cfindings("l101_same_lock_deadlock.py") == [("L101", 11)]


def test_l101_consistent_order_and_rlock_clean():
    assert _cfindings("l101_consistent.py") == []


def test_l101_race_waiver_suppresses():
    assert _cfindings("l101_waived.py") == []


def test_l102_blocking_under_lock_fires():
    assert _cfindings("l102_blocking.py") == [
        ("L102", 16), ("L102", 17), ("L102", 22), ("L102", 23)]


def test_l102_cv_wait_and_unlocked_blocking_clean():
    assert _cfindings("l102_clean.py") == []


def test_l103_shared_view_mutation_fires():
    assert _cfindings("l103_mutate.py") == [
        ("L103", 10), ("L103", 15), ("L103", 20)]


def test_l103_deepcopy_and_own_list_clean():
    assert _cfindings("l103_deepcopy.py") == []


def test_l104_update_accelerator_regression_shape_fires():
    """The PR-1 bug: fleet-index invalidation outside the discovery
    lock let a concurrent scan install a stale snapshot (DNS
    convergence stalled for a TTL)."""
    assert _cfindings("l104_update_accelerator_regression.py") == [
        ("L104", 21), ("L104", 22), ("L104", 25), ("L104", 26)]


def test_l104_locked_discipline_clean():
    assert _cfindings("l104_locked.py") == []


def test_l104_singleflight_key_without_gen_fires():
    assert _cfindings("l104_singleflight_nogen.py") == [
        ("L104", 11), ("L104", 15)]


def test_l105_direct_api_call_fires_and_waiver_suppresses():
    """Bare service calls (no ``apis`` in the receiver chain) fire;
    the ``# race:`` waiver spelling suppresses line 15's deliberate
    bare read."""
    assert _cfindings("l105_direct_api.py") == [
        ("L105", 12), ("L105", 13), ("L105", 14)]


def test_l105_wrapped_calls_clean():
    assert _cfindings("l105_clean.py") == []


def test_l106_direct_mutation_fires_and_waiver_suppresses():
    """Mutations on the write-coalescing surface fire even through
    ``apis`` (where L105 is silent); the ``# race:`` waiver suppresses
    line 17's deliberate direct replace."""
    assert _cfindings("l106_direct_mutation.py") == [
        ("L106", 12), ("L106", 14), ("L106", 16)]


def test_l106_coalescer_submits_clean():
    assert _cfindings("l106_clean.py") == []


def test_l106_batcher_module_exempt():
    """The coalescer itself is the one legitimate issuer of the
    batched mutation calls — the shipped batcher.py must stay clean
    under its own rule."""
    batcher_py = pathlib.Path(ROOT_DIR) / (
        "aws_global_accelerator_controller_tpu/cloudprovider/aws/"
        "batcher.py")
    assert concurrency_lint.lint_files([batcher_py]) == []


def test_l107_apis_in_fingerprint_fires_and_waiver_suppresses():
    """Provider calls in fingerprint builders fire — through ``apis``
    (L105 silent) at 13/14, bare at 22 (both rules); line 15's
    deliberate probe is waived."""
    assert _cfindings("l107_apis_in_fingerprint.py") == [
        ("L107", 13), ("L107", 14), ("L105", 22), ("L107", 22)]


def test_l107_informer_only_builders_clean():
    assert _cfindings("l107_clean.py") == []


def test_l107_reconcile_package_clean():
    """The shipped fast path itself (the reconcile package: dispatch +
    fingerprint cache) must stay provider-free under its own rule."""
    pkg = pathlib.Path(ROOT_DIR) / (
        "aws_global_accelerator_controller_tpu/reconcile")
    files = sorted(pkg.glob("*.py"))
    assert files, "reconcile package files not found"
    assert concurrency_lint.lint_files(files) == []


def test_l107_seeded_apis_call_in_shipped_builder_caught(tmp_path):
    """Acceptance probe tied to the shipped code shape: graft an
    ``apis`` read into the REAL GA service fingerprint builder and the
    gate must fire."""
    ga_py = pathlib.Path(ROOT_DIR) / (
        "aws_global_accelerator_controller_tpu/controller/"
        "globalaccelerator.py")
    src = ga_py.read_text()
    needle = "    ports, protocol = listener_for_service(svc)\n"
    assert src.count(needle) == 1, \
        "ga_service_fingerprint shape changed; update this probe"
    mutated = src.replace(
        needle,
        needle + "    svc.apis.ga.describe_accelerator(svc.key())\n")
    # keep the package-scope marker in the path so the rule applies
    pkg_dir = (tmp_path / "aws_global_accelerator_controller_tpu"
               / "controller")
    pkg_dir.mkdir(parents=True)
    f = pkg_dir / "globalaccelerator.py"
    f.write_text(mutated)
    findings = [x for x in concurrency_lint.lint_files([f])
                if x.code == "L107"]
    assert findings, "seeded apis call in a fingerprint builder " \
                     "was not caught"


def test_l105_out_of_scope_paths_exempt(tmp_path):
    """Tests and tools observe the fake cloud directly by design —
    the rule only polices the shipped package (and its fixtures)."""
    f = tmp_path / "observer.py"
    f.write_text("def peek(cloud):\n"
                 "    return cloud.ga.list_accelerators()\n")
    assert concurrency_lint.lint_files([f]) == []


def test_seeded_mutation_of_update_accelerator_is_caught(tmp_path):
    """Acceptance probe: drop the ``with self._s.lock:`` block from the
    REAL provider's ``_update_accelerator`` and the gate must fire —
    the lint is tied to the shipped code shape, not just fixtures."""
    provider_py = pathlib.Path(ROOT_DIR) / (
        "aws_global_accelerator_controller_tpu/cloudprovider/aws/"
        "provider.py")
    src = provider_py.read_text()
    start = src.index("def _update_accelerator")
    end = src.index("def get_listener")
    body = src[start:end]
    assert body.count("with self._s.lock:") == 1
    mutated = src[:start] \
        + body.replace("with self._s.lock:", "if True:") + src[end:]
    f = tmp_path / "provider_mutated.py"
    f.write_text(mutated)
    codes = [c for c, _ in
             [(x.code, x.line)
              for x in concurrency_lint.lint_files([f])]]
    assert codes.count("L104") >= 2, codes  # both *_locked calls bare

    # sanity: the unmutated file is clean (the tree gate's per-file view)
    assert concurrency_lint.lint_files([provider_py]) == []


def test_l108_unfenced_bare_write_fires_and_waiver_suppresses():
    """Bare AWS writes with no lexical fence consult fire L108 (and
    L105 — a bare write is doubly wrong); the ``# race:`` waiver
    suppresses line 17's deliberate teardown call."""
    got = _cfindings("l108_unfenced_write.py")
    assert [(c, l) for c, l in got if c == "L108"] == [
        ("L108", 7), ("L108", 8), ("L108", 12)]


def test_l108_fenced_and_apis_routed_writes_clean():
    """A lexical fence.check, a flush_pass drain window, and a write
    routed through ``apis`` (runtime-gated by ResilientAPIs.invoke)
    are all clean under L108."""
    assert _cfindings("l108_fenced_write.py") == []


def test_l109_raw_enqueue_fires_and_waiver_suppresses():
    """Class-less workqueue enqueues from controller/reconcile-scoped
    code fire L109; the ``# race:`` waiver suppresses the deliberate
    raw add at the bottom of the fixture."""
    got = _cfindings("l109_raw_enqueue.py")
    assert [(c, l) for c, l in got if c == "L109"] == [
        ("L109", 8), ("L109", 9), ("L109", 13)]


def test_l109_class_tagged_enqueues_clean():
    """klass= tags, CLASS_KEEP requeues, and non-queue ``.add`` calls
    (sets, lists) are all clean under L109."""
    assert _cfindings("l109_clean.py") == []


def test_l109_controller_packages_clean_under_own_rule():
    """The shipped enqueue sites themselves (controller/ + reconcile/)
    must stay class-tagged under their own rule."""
    for rel in ("aws_global_accelerator_controller_tpu/controller",
                "aws_global_accelerator_controller_tpu/reconcile"):
        pkg = pathlib.Path(ROOT_DIR) / rel
        files = sorted(pkg.glob("*.py"))
        assert files, f"{rel} files not found"
        assert [x for x in concurrency_lint.lint_files(files)
                if x.code == "L109"] == []


def test_l109_seeded_raw_enqueue_in_shipped_controller_caught(tmp_path):
    """Acceptance probe tied to the shipped code shape: strip the
    klass= tag from the REAL shared event-enqueue helper (base.py
    ``event_enqueue`` — every controller handler routes through it)
    and the gate must fire."""
    base_py = pathlib.Path(ROOT_DIR) / (
        "aws_global_accelerator_controller_tpu/controller/base.py")
    src = base_py.read_text()
    needle = ("    queue.add_rate_limited(key, klass=CLASS_INTERACTIVE,"
              " ctx=ctx)")
    assert src.count(needle) >= 1, \
        "shared event-enqueue shape changed; update this probe"
    mutated = src.replace(
        needle, "    queue.add_rate_limited(key, ctx=ctx)", 1)
    pkg_dir = (tmp_path / "aws_global_accelerator_controller_tpu"
               / "controller")
    pkg_dir.mkdir(parents=True)
    f = pkg_dir / "base.py"
    f.write_text(mutated)
    findings = [x for x in concurrency_lint.lint_files([f])
                if x.code == "L109"]
    assert findings, "a class-less shipped enqueue was not caught"


def test_l110_unchecked_bare_write_fires_and_waiver_suppresses():
    """Bare AWS writes with no lexical shard-ownership consult fire
    L110; the ``# race:`` waiver suppresses the deliberate teardown
    call at the bottom of the fixture."""
    got = _cfindings("l110_unchecked_write.py")
    assert [(c, l) for c, l in got if c == "L110"] == [
        ("L110", 9), ("L110", 10), ("L110", 15)]


def test_l110_shard_checked_writes_clean():
    """A lexical shards.check, an owns_key pre-check, a routed
    dispatch guard, and a write through ``apis`` are all clean under
    L110."""
    assert [x for x in _cfindings("l110_checked_write.py")
            if x[0] == "L110"] == []


def test_l110_seeded_shard_check_strip_from_batcher_caught(tmp_path):
    """Acceptance probe tied to the shipped code shape: strip the
    shard-ownership assertion from the REAL ShardedCoalescer submit
    path and the gate must fire — every coalesced mutation in the
    tree relies on that one line to keep one writer per endpoint
    group / hosted zone."""
    batcher_py = pathlib.Path(ROOT_DIR) / (
        "aws_global_accelerator_controller_tpu/cloudprovider/aws/"
        "batcher.py")
    src = batcher_py.read_text()
    needle = ('        sid = self._shards.check(container_key, '
              'surface="coalescer")\n')
    assert src.count(needle) == 1, \
        "ShardedCoalescer submit-gate shape changed; update this probe"
    mutated = src.replace(needle, "        sid = 0\n")
    pkg_dir = (tmp_path / "aws_global_accelerator_controller_tpu"
               / "cloudprovider" / "aws")
    pkg_dir.mkdir(parents=True)
    f = pkg_dir / "batcher.py"
    f.write_text(mutated)
    findings = [x for x in concurrency_lint.lint_files([f])
                if x.code == "L110"]
    assert findings, "a shard-check-less ShardedCoalescer was not caught"

    # sanity: the unmutated batcher is clean under its own rule
    assert [x for x in concurrency_lint.lint_files([batcher_py])
            if x.code == "L110"] == []


def test_l108_seeded_fence_strip_from_wrapper_caught(tmp_path):
    """Acceptance probe tied to the shipped code shape: strip the
    fence consult from the REAL ResilientAPIs.invoke and the gate must
    fire — every apis.* write in the tree relies on that one line."""
    wrapper_py = pathlib.Path(ROOT_DIR) / (
        "aws_global_accelerator_controller_tpu/resilience/wrapper.py")
    src = wrapper_py.read_text()
    needle = ("                if op in MUTATION_METHODS:\n"
              "                    if self.fence is not None:\n"
              "                        self.fence.check(\"wrapper\")\n"
              "                    for extra_fence in "
              "active_write_fences():\n"
              "                        extra_fence.check(\"wrapper\")\n")
    assert src.count(needle) == 1, \
        "ResilientAPIs.invoke fence-gate shape changed; update this probe"
    mutated = src.replace(needle, "                pass\n")
    pkg_dir = (tmp_path / "aws_global_accelerator_controller_tpu"
               / "resilience")
    pkg_dir.mkdir(parents=True)
    f = pkg_dir / "wrapper.py"
    f.write_text(mutated)
    findings = [x for x in concurrency_lint.lint_files([f])
                if x.code == "L108"]
    assert findings, "a fence-less ResilientAPIs.invoke was not caught"

    # sanity: the unmutated wrapper is clean under its own rule
    assert [x for x in concurrency_lint.lint_files([wrapper_py])
            if x.code == "L108"] == []


def test_l111_direct_pltpu_and_orbax_fire_and_waiver_suppresses():
    """Direct imports of the drifting modules fire (lines 4/5), as do
    bare ``pltpu.*`` attribute chains without an import in sight
    (12/14 — the grafted-call shape) and the through-the-alias
    ``pl.tpu.X`` shape (31); the ``# race:`` waiver suppresses line
    22's deliberate drift probe."""
    assert _cfindings("l111_direct_pltpu.py") == [
        ("L111", 4), ("L111", 5), ("L111", 12), ("L111", 14),
        ("L111", 31)]


def test_l111_shimmed_access_clean():
    assert _cfindings("l111_clean.py") == []


def test_l111_accelerator_packages_clean():
    """The shipped accelerator stack must stay clean under its own
    rule: no direct pltpu/orbax access outside compat/."""
    for pkg in ("ops", "models", "parallel", "cmd"):
        d = pathlib.Path(ROOT_DIR) / (
            "aws_global_accelerator_controller_tpu/" + pkg)
        files = sorted(d.glob("*.py"))
        assert files, f"{pkg} package files not found"
        found = [x for x in concurrency_lint.lint_files(files)
                 if x.code == "L111"]
        assert found == [], found


def test_l111_compat_package_exempt():
    """compat/ IS the legitimate home of raw pltpu/orbax access —
    the shim must never fire its own rule."""
    d = pathlib.Path(ROOT_DIR) / (
        "aws_global_accelerator_controller_tpu/compat")
    files = sorted(d.glob("*.py"))
    assert files, "compat package files not found"
    assert [x for x in concurrency_lint.lint_files(files)
            if x.code == "L111"] == []


def test_l111_seeded_pltpu_graft_into_shipped_ops_caught(tmp_path):
    """Acceptance probe tied to the shipped code shape: graft a bare
    ``pltpu.CompilerParams`` back into the REAL flash-attention kernel
    (the exact drift that wedged the track for 150 tier-1 failures)
    and the gate must fire."""
    ops_py = pathlib.Path(ROOT_DIR) / (
        "aws_global_accelerator_controller_tpu/ops/"
        "pallas_attention.py")
    src = ops_py.read_text()
    needle = "        compiler_params=CompilerParams(\n"
    assert src.count(needle) >= 1, \
        "flash kernel compiler_params shape changed; update this probe"
    mutated = src.replace(
        needle, "        compiler_params=pltpu.CompilerParams(\n", 1)
    pkg_dir = (tmp_path / "aws_global_accelerator_controller_tpu"
               / "ops")
    pkg_dir.mkdir(parents=True)
    f = pkg_dir / "pallas_attention.py"
    f.write_text(mutated)
    findings = [x for x in concurrency_lint.lint_files([f])
                if x.code == "L111"]
    assert findings, "a grafted bare pltpu.CompilerParams in shipped " \
                     "ops code was not caught"

    # sanity: the unmutated kernel is clean under its own rule
    assert [x for x in concurrency_lint.lint_files([ops_py])
            if x.code == "L111"] == []


def test_l112_ungated_weight_mutation_fires():
    """A weight mutation with no rollout consult in the enclosing
    function snaps mid-ramp objects to their target — both spellings
    of the surface fire."""
    assert _cfindings("l112_snap.py") == [("L112", 13), ("L112", 17)]


def test_l112_gated_and_waived_clean():
    """The consult shapes `_consults_rollout` recognizes — the engine
    call, a `*rollout*` helper — and a `# race:` waived deliberate
    snap are all clean."""
    assert _cfindings("l112_gated.py") == []


def test_l112_rollout_package_exempt():
    """rollout/ itself (the machine that plans the weights everyone
    else gates on) is exempt from its own rule."""
    pkg = pathlib.Path(ROOT_DIR) / "aws_global_accelerator_controller_tpu"
    files = sorted((pkg / "rollout").glob("*.py"))
    assert files, "rollout package missing?"
    assert [x for x in concurrency_lint.lint_files(files)
            if x.code == "L112"] == []


def test_l112_shipped_controllers_clean():
    """The real weight-bearing controllers carry their consults."""
    pkg = pathlib.Path(ROOT_DIR) / "aws_global_accelerator_controller_tpu"
    files = [pkg / "controller" / "endpointgroupbinding.py",
             pkg / "controller" / "route53.py"]
    assert [x for x in concurrency_lint.lint_files(files)
            if x.code == "L112"] == []


def test_l112_seeded_rollout_strip_from_egb_controller_caught(tmp_path):
    """Acceptance probe tied to the shipped code shape: strip the
    rollout consult from the REAL EndpointGroupBinding weight-apply
    path and the gate must fire — every EG-weight ramp in the fleet
    relies on that consult to keep mid-ramp weights in force."""
    egb_py = pathlib.Path(ROOT_DIR) / (
        "aws_global_accelerator_controller_tpu/controller/"
        "endpointgroupbinding.py")
    src = egb_py.read_text()
    needle = "        outcome = self.rollout.decide(\n"
    assert src.count(needle) == 1, \
        "EGB weight-apply rollout-gate shape changed; update this probe"
    # replace the consult with a passthrough outcome of the same name
    mutated = src.replace(
        needle, "        outcome = _Passthrough(\n")
    # _rollout_declared still mentions rollout; strip it too so the
    # probe proves the RULE fires, not a coincidental helper name
    mutated = mutated.replace("not self._rollout_declared(obj)",
                              "True")
    pkg_dir = (tmp_path / "aws_global_accelerator_controller_tpu"
               / "controller")
    pkg_dir.mkdir(parents=True)
    f = pkg_dir / "endpointgroupbinding.py"
    f.write_text(mutated)
    findings = [x for x in concurrency_lint.lint_files([f])
                if x.code == "L112"]
    assert findings, "a rollout-gate-less EGB weight apply was not caught"


def test_l112_seeded_rollout_strip_from_route53_controller_caught(
        tmp_path):
    """The route53 twin: strip `_record_rollout` from the service
    process func and the shipped-consult check must fire."""
    r53_py = pathlib.Path(ROOT_DIR) / (
        "aws_global_accelerator_controller_tpu/controller/route53.py")
    src = r53_py.read_text()
    needle = ("        policy, ramp_weights, ramp_requeue = "
              "self._record_rollout(\n"
              "            svc, \"service\", hostnames, "
              "self.kube_client.services)\n")
    assert src.count(needle) == 1, \
        "route53 service rollout-gate shape changed; update this probe"
    mutated = src.replace(
        needle,
        "        policy, ramp_weights, ramp_requeue = None, None, 0.0\n")
    pkg_dir = (tmp_path / "aws_global_accelerator_controller_tpu"
               / "controller")
    pkg_dir.mkdir(parents=True)
    f = pkg_dir / "route53.py"
    f.write_text(mutated)
    findings = [x for x in concurrency_lint.lint_files([f])
                if x.code == "L112"
                and "process_service_create_or_update" in x.msg]
    assert findings, "a rollout-gate-less route53 service process " \
                     "func was not caught"


def test_l113_impure_planner_fires_and_waiver_suppresses():
    """Provider reach (line 9) and device-program Python loops
    (14/16 in the ``_device_*`` shape, 31 through a ``jit``
    decoration) fire; the host-side pack loop (line 8) does not, and
    the ``# race:`` waiver suppresses line 39's deliberate probe."""
    assert _cfindings("l113_impure_planner.py") == [
        ("L113", 9), ("L113", 14), ("L113", 16), ("L113", 31)]


def test_l113_clean_planner_shapes_pass():
    """Host-side pack/decode loops and pure-array device programs are
    the supported shapes — zero findings."""
    assert _cfindings("l113_clean.py") == []


def test_l113_shipped_planner_modules_clean():
    """The shipped columnar planner stays clean under its own rule."""
    files = [pathlib.Path(ROOT_DIR) / p for p in (
        "aws_global_accelerator_controller_tpu/parallel/fleet_plan.py",
        "aws_global_accelerator_controller_tpu/reconcile/columnar.py")]
    assert [x for x in concurrency_lint.lint_files(files)
            if x.code == "L113"] == []


def test_l113_seeded_loop_graft_into_shipped_planner_caught(tmp_path):
    """Acceptance probe tied to the shipped code shape: graft a
    per-row Python loop back into the REAL device program
    (``_device_plan_block``) and the gate must fire — that loop is
    exactly the object-at-a-time planning the columnar pass deleted."""
    plan_py = pathlib.Path(ROOT_DIR) / (
        "aws_global_accelerator_controller_tpu/parallel/fleet_plan.py")
    src = plan_py.read_text()
    needle = "    s = score_rows(params, rows)"
    assert src.count(needle) == 1, \
        "device program scoring shape changed; update this probe"
    mutated = src.replace(
        needle,
        "    for _row in rows:\n        pass\n" + needle, 1)
    pkg_dir = (tmp_path / "aws_global_accelerator_controller_tpu"
               / "parallel")
    pkg_dir.mkdir(parents=True)
    f = pkg_dir / "fleet_plan.py"
    f.write_text(mutated)
    findings = [x for x in concurrency_lint.lint_files([f])
                if x.code == "L113" and "loop" in x.msg]
    assert findings, "a grafted Python loop in the shipped device " \
                     "program was not caught"


def test_l113_seeded_apis_graft_into_packing_caught(tmp_path):
    """The other half: graft a provider describe into the REAL packing
    layer (``pack_fleet``) and the purity gate must fire."""
    col_py = pathlib.Path(ROOT_DIR) / (
        "aws_global_accelerator_controller_tpu/reconcile/columnar.py")
    src = col_py.read_text()
    needle = "    table = InternTable()\n"
    assert src.count(needle) == 1, \
        "pack_fleet intern-table shape changed; update this probe"
    mutated = src.replace(
        needle,
        needle + "    apis.ga.describe_endpoint_group(groups[0])\n", 1)
    pkg_dir = (tmp_path / "aws_global_accelerator_controller_tpu"
               / "reconcile")
    pkg_dir.mkdir(parents=True)
    f = pkg_dir / "columnar.py"
    f.write_text(mutated)
    findings = [x for x in concurrency_lint.lint_files([f])
                if x.code == "L113" and "provider call" in x.msg]
    assert findings, "a grafted apis reach in the shipped packing " \
                     "layer was not caught"


# -- L114: trace-context propagation on the enqueue surface ------------


def test_l114_dropped_ctx_fires_and_waiver_suppresses():
    """Enqueues without ctx= from controller/reconcile-scoped code
    fire L114 (the class tags are present, so L114 fires ALONE); the
    ``# race:`` waiver suppresses the deliberate untraced enqueue."""
    got = _cfindings("l114_dropped_ctx.py")
    assert [(c, l) for c, l in got if c == "L114"] == [
        ("L114", 13), ("L114", 17), ("L114", 18)]
    assert not [c for c, _ in got if c == "L109"], \
        "fixture should be class-tagged (L114 must fire alone)"


def test_l114_propagating_enqueues_clean():
    """ctx= propagation — minted, continued, or an explicit
    ctx=None — is clean under L114."""
    assert _cfindings("l114_clean.py") == []


def test_l114_controller_packages_clean_under_own_rule():
    """Every shipped enqueue site (controller/ + reconcile/) must
    propagate a TraceContext under its own rule."""
    for rel in ("aws_global_accelerator_controller_tpu/controller",
                "aws_global_accelerator_controller_tpu/reconcile"):
        pkg = pathlib.Path(ROOT_DIR) / rel
        files = sorted(pkg.glob("*.py"))
        assert files, f"{rel} files not found"
        assert [x for x in concurrency_lint.lint_files(files)
                if x.code == "L114"] == []


def test_l114_seeded_ctx_strip_in_shipped_enqueue_caught(tmp_path):
    """Acceptance probe tied to the shipped code shape: strip the
    ctx= propagation from the REAL shared event-enqueue helper
    (base.py) and the gate must fire."""
    base_py = pathlib.Path(ROOT_DIR) / (
        "aws_global_accelerator_controller_tpu/controller/base.py")
    src = base_py.read_text()
    needle = ("    queue.add_rate_limited(key, klass=CLASS_INTERACTIVE,"
              " ctx=ctx)")
    assert src.count(needle) >= 1, \
        "shared event-enqueue shape changed; update this probe"
    mutated = src.replace(
        needle,
        "    queue.add_rate_limited(key, klass=CLASS_INTERACTIVE)", 1)
    pkg_dir = (tmp_path / "aws_global_accelerator_controller_tpu"
               / "controller")
    pkg_dir.mkdir(parents=True)
    f = pkg_dir / "base.py"
    f.write_text(mutated)
    findings = [x for x in concurrency_lint.lint_files([f])
                if x.code == "L114"]
    assert findings, "a trace-dropping shipped enqueue was not caught"


def test_l114_seeded_ambient_capture_strip_in_batcher_caught(tmp_path):
    """The runtime-gate half: strip the ambient_context() capture from
    the REAL coalescer submit path and the batcher gate must fire
    whenever batcher.py is in the linted set."""
    batcher_py = pathlib.Path(ROOT_DIR) / (
        "aws_global_accelerator_controller_tpu/cloudprovider/aws/"
        "batcher.py")
    src = batcher_py.read_text()
    needle = "        ctx = ambient_context()\n"
    assert src.count(needle) == 1, \
        "coalescer submit trace capture shape changed; update probe"
    mutated = src.replace(needle, "        ctx = None\n", 1)
    pkg_dir = (tmp_path / "aws_global_accelerator_controller_tpu"
               / "cloudprovider" / "aws")
    pkg_dir.mkdir(parents=True)
    f = pkg_dir / "batcher.py"
    f.write_text(mutated)
    findings = [x for x in concurrency_lint.lint_files([f])
                if x.code == "L114"]
    assert findings, "a stripped ambient-context capture was not caught"


def test_l114_batcher_gate_trusts_shipped_when_absent(tmp_path):
    """A fixture subset without batcher.py must not fire the
    coalescer-trace gate (parity with the other module gates)."""
    findings = [x for x in concurrency_lint.lint_files(
        [FIXTURES / "l114_clean.py"]) if x.code == "L114"]
    assert findings == []


def test_l115_wall_clock_leaks_fire_and_waiver_suppresses():
    """Direct time reads/sleeps (9-11), a literal-timeout wait (12),
    raw threading primitives (17-18) and a literal kwarg timeout (19)
    fire; the ``# race:`` waiver suppresses the deliberate boundary
    sleep."""
    got = [x for x in _cfindings("l115_leaky.py") if x[0] == "L115"]
    assert got == [("L115", 9), ("L115", 10), ("L115", 11),
                   ("L115", 12), ("L115", 17), ("L115", 18),
                   ("L115", 19)]


def test_l115_clock_aware_shapes_pass():
    """simclock reads, make_event, named/derived wait bounds and
    untimed waits are the supported shapes — zero findings."""
    assert [x for x in _cfindings("l115_clean.py")
            if x[0] == "L115"] == []


def test_l115_clock_owned_packages_clean():
    """Every shipped clock-owned package is L115-clean: the whole
    point of the rule is that NO wall-clock read survives outside
    simulation/clock.py and the waiver-listed real-I/O shims."""
    roots = [
        "aws_global_accelerator_controller_tpu/kube",
        "aws_global_accelerator_controller_tpu/resilience",
        "aws_global_accelerator_controller_tpu/cloudprovider",
        "aws_global_accelerator_controller_tpu/leaderelection",
        "aws_global_accelerator_controller_tpu/reconcile",
        "aws_global_accelerator_controller_tpu/rollout",
        "aws_global_accelerator_controller_tpu/controller",
        "aws_global_accelerator_controller_tpu/manager",
        "aws_global_accelerator_controller_tpu/sharding",
        "aws_global_accelerator_controller_tpu/tracing.py",
        "aws_global_accelerator_controller_tpu/flight.py",
        "aws_global_accelerator_controller_tpu/metrics.py",
    ]
    files = []
    for r in roots:
        p = pathlib.Path(ROOT_DIR) / r
        files.extend(sorted(p.rglob("*.py")) if p.is_dir() else [p])
    findings = [x for x in concurrency_lint.lint_files(files)
                if x.code == "L115"]
    assert findings == [], findings


def test_l115_seeded_bare_sleep_in_shipped_informer_caught(tmp_path):
    """Acceptance probe (ISSUE 13): graft a bare ``time.sleep`` back
    into the REAL informer loop — the exact leak class that silently
    breaks virtual-time determinism — and the rule must fire."""
    inf_py = pathlib.Path(ROOT_DIR) / (
        "aws_global_accelerator_controller_tpu/kube/informers.py")
    src = inf_py.read_text()
    needle = "                self._resync_due(spread)\n"
    assert src.count(needle) == 1, \
        "informer loop shape changed; update this probe"
    mutated = src.replace(
        needle,
        "                import time\n"
        "                time.sleep(0.001)\n" + needle, 1)
    pkg_dir = tmp_path / "aws_global_accelerator_controller_tpu" / "kube"
    pkg_dir.mkdir(parents=True)
    f = pkg_dir / "informers.py"
    f.write_text(mutated)
    findings = [x for x in concurrency_lint.lint_files([f])
                if x.code == "L115" and "time.sleep" in x.msg]
    assert findings, "a grafted bare time.sleep in the shipped " \
                     "informer loop was not caught"


def test_l116_flat_fanin_fires():
    """A direct cross-region wire call (apply_region_batch) outside
    topology/ is flat fan-in without the aggregator's contracts."""
    assert ("L116", 11) in _cfindings("l116_flat_fanin.py")


def test_l116_clean_passes():
    assert [x for x in _cfindings("l116_clean.py")
            if x[0] == "L116"] == []


def test_l116_topology_package_exempt():
    """The aggregator's own module (the one legitimate issuer) is
    exempt — and clean under every other rule."""
    agg = pathlib.Path(ROOT_DIR) / (
        "aws_global_accelerator_controller_tpu/topology/aggregator.py")
    assert [x for x in concurrency_lint.lint_files([agg])
            if x.code == "L116"] == []


def test_l116_seeded_handoff_strip_from_batcher_caught(tmp_path):
    """Acceptance probe tied to the shipped code shape: strip the
    ShardedCoalescer→aggregator handoff consult from the REAL wire
    path and the gate must fire whenever batcher.py is linted — with
    a topology configured, every coalesced mutation relies on that
    consult to ride the per-region fan-in instead of flat
    cross-region calls."""
    batcher_py = pathlib.Path(ROOT_DIR) / (
        "aws_global_accelerator_controller_tpu/cloudprovider/aws/"
        "batcher.py")
    src = batcher_py.read_text()
    needle = ("        if self._aggregator is not None:\n"
              "            self._aggregator.submit_record_sets(\n"
              "                zone_id, changes, fence=self._fence, "
              "ctxs=ctxs,\n"
              "                shard_id=self._shard_id)\n"
              "            return\n")
    assert src.count(needle) == 1, \
        "coalescer wire-handoff shape changed; update this probe"
    mutated = src.replace(needle, "", 1)
    pkg_dir = (tmp_path / "aws_global_accelerator_controller_tpu"
               / "cloudprovider" / "aws")
    pkg_dir.mkdir(parents=True)
    f = pkg_dir / "batcher.py"
    f.write_text(mutated)
    findings = [x for x in concurrency_lint.lint_files([f])
                if x.code == "L116"]
    assert findings, "a stripped aggregator handoff was not caught"

    # sanity: the unmutated batcher is clean under its own rule
    assert [x for x in concurrency_lint.lint_files([batcher_py])
            if x.code == "L116"] == []


def test_l116_batcher_gate_trusts_shipped_when_absent():
    """A fixture subset without batcher.py must not fire the handoff
    gate (parity with the other module gates)."""
    assert [x for x in _cfindings("l116_clean.py")
            if x[0] == "L116"] == []


# ---------------------------------------------------------------------------
# L117: registry-owned knobs must not be re-hardcoded (ISSUE 15)
# ---------------------------------------------------------------------------

def test_l117_hardcoded_knob_literals_fire():
    """Every flagged shape: two signature defaults (line 7), a
    suffix-matched module assignment (12), a keyword literal (16), an
    attribute assignment (17) and a plain local assignment (18)."""
    got = [x for x in _cfindings("l117_hardcoded.py") if x[0] == "L117"]
    assert got == [("L117", 7), ("L117", 7), ("L117", 12),
                   ("L117", 16), ("L117", 17), ("L117", 18)], got


def test_l117_clean_spellings_pass():
    """Catalog-constant defaults, non-knob numerics and the ``# race:``
    waiver on a deliberate divergent test profile — zero findings."""
    assert [x for x in _cfindings("l117_clean.py")
            if x[0] == "L117"] == []


def test_l117_autotune_package_exempt():
    """The catalog itself is the one legitimate home of the numeric
    spellings — knobs.py (and the rest of autotune/) never fires."""
    auto_dir = pathlib.Path(ROOT_DIR) / (
        "aws_global_accelerator_controller_tpu/autotune")
    files = sorted(auto_dir.rglob("*.py"))
    assert files, "autotune package missing"
    assert [x for x in concurrency_lint.lint_files(files)
            if x.code == "L117"] == []


def test_l117_clock_owned_packages_clean():
    """The retrofit proof: every clock-owned package spells its knob
    defaults through the catalog — zero L117 findings tree-wide."""
    roots = [
        "aws_global_accelerator_controller_tpu/kube",
        "aws_global_accelerator_controller_tpu/resilience",
        "aws_global_accelerator_controller_tpu/cloudprovider",
        "aws_global_accelerator_controller_tpu/leaderelection",
        "aws_global_accelerator_controller_tpu/reconcile",
        "aws_global_accelerator_controller_tpu/rollout",
        "aws_global_accelerator_controller_tpu/controller",
        "aws_global_accelerator_controller_tpu/manager",
        "aws_global_accelerator_controller_tpu/sharding",
        "aws_global_accelerator_controller_tpu/topology",
        "aws_global_accelerator_controller_tpu/tracing.py",
        "aws_global_accelerator_controller_tpu/flight.py",
        "aws_global_accelerator_controller_tpu/metrics.py",
    ]
    files = []
    for r in roots:
        p = pathlib.Path(ROOT_DIR) / r
        files.extend(sorted(p.rglob("*.py")) if p.is_dir() else [p])
    findings = [x for x in concurrency_lint.lint_files(files)
                if x.code == "L117"]
    assert findings == [], findings


def test_l117_seeded_literal_linger_in_shipped_batcher_caught(tmp_path):
    """Acceptance probe (ISSUE 15): graft the literal linger default
    back into the REAL batcher.py — the exact re-hardcoding the rule
    exists to block — and the rule must fire."""
    batcher_py = pathlib.Path(ROOT_DIR) / (
        "aws_global_accelerator_controller_tpu/cloudprovider/aws/"
        "batcher.py")
    src = batcher_py.read_text()
    needle = "    linger: float = knobcat.COALESCER_LINGER\n"
    assert src.count(needle) == 1, \
        "CoalesceConfig linger spelling changed; update this probe"
    mutated = src.replace(needle, "    linger: float = 0.005\n", 1)
    pkg_dir = (tmp_path / "aws_global_accelerator_controller_tpu"
               / "cloudprovider" / "aws")
    pkg_dir.mkdir(parents=True)
    f = pkg_dir / "batcher.py"
    f.write_text(mutated)
    findings = [x for x in concurrency_lint.lint_files([f])
                if x.code == "L117"]
    assert findings, "a grafted literal linger default in the " \
                     "shipped batcher was not caught"

    # sanity: the unmutated batcher is clean under the rule
    assert [x for x in concurrency_lint.lint_files([batcher_py])
            if x.code == "L117"] == []


def test_l118_wave_repack_fires_and_oracle_shapes_pass():
    """Full repacks on the wave path (lines 9/10, plus the
    module-level call at 24) fire; the oracle/verify functions and
    the ``# race:`` waiver are the legal shapes."""
    assert _cfindings("l118_wave_repack.py") == [
        ("L118", 9), ("L118", 10), ("L118", 24)]


def test_l118_clean_wave_path_passes():
    """plan_wave-only waves and repacks behind oracle/verify entry
    points (nested helpers included) — zero findings."""
    assert _cfindings("l118_clean.py") == []


def test_l118_shipped_wave_path_modules_clean():
    """The shipped steady-state wave path stays clean under its own
    rule."""
    files = [pathlib.Path(ROOT_DIR) / p for p in (
        "aws_global_accelerator_controller_tpu/controller/"
        "fleetsweep.py",
        "aws_global_accelerator_controller_tpu/parallel/overlap.py")]
    assert [x for x in concurrency_lint.lint_files(files)
            if x.code == "L118"] == []


def test_l118_seeded_repack_graft_into_shipped_sweep_caught(tmp_path):
    """Acceptance probe (ISSUE 16): graft a full repack back into the
    REAL sweep wave (``plan_staged``) — the exact regression the rule
    exists to block — and the gate must fire."""
    sweep_py = pathlib.Path(ROOT_DIR) / (
        "aws_global_accelerator_controller_tpu/controller/"
        "fleetsweep.py")
    src = sweep_py.read_text()
    needle = "                wave = planner.plan_wave()\n"
    assert src.count(needle) == 1, \
        "sweep wave planning shape changed; update this probe"
    mutated = src.replace(
        needle,
        "                packed = pack_fleet(\n"
        "                    fleet.snapshot_groups())\n" + needle, 1)
    pkg_dir = (tmp_path / "aws_global_accelerator_controller_tpu"
               / "controller")
    pkg_dir.mkdir(parents=True)
    f = pkg_dir / "fleetsweep.py"
    f.write_text(mutated)
    findings = [x for x in concurrency_lint.lint_files([f])
                if x.code == "L118"]
    assert findings, "a grafted full repack in the shipped sweep " \
                     "wave was not caught"


# ---------------------------------------------------------------------------
# L119/L120: field-level lock-ownership contracts (analysis/ownership.py)
# ---------------------------------------------------------------------------

def test_l119_guarded_accesses_clean():
    """Lock-held accesses, *_locked methods, immutable reads, internal
    method calls and the ``# race:`` waiver — zero findings."""
    assert [x for x in _cfindings("l119_guarded.py")
            if x[0] == "L119"] == []


def test_l119_unguarded_accesses_fire():
    """A lock-free write (13), a bare read (16) and a post-init rebind
    of an ``immutable`` field (19) all fire."""
    assert [x for x in _cfindings("l119_unguarded.py")
            if x[0] == "L119"] == [
        ("L119", 13), ("L119", 16), ("L119", 19)]


def test_l120_declared_crossing_class_clean():
    """A thread-spawning class whose mutable fields all carry
    declarations (lock / external / waiver) never fires."""
    assert [x for x in _cfindings("l120_owned.py")
            if x[0] == "L120"] == []


def test_l120_undeclared_crossing_class_fires():
    """Instances cross threads and two mutable fields carry no
    declaration: one finding per field at its first mutation."""
    assert [x for x in _cfindings("l120_crossing.py")
            if x[0] == "L120"] == [("L120", 17), ("L120", 18)]


def test_l119_seeded_lock_strip_from_shipped_shardset_caught():
    """Acceptance probe (via the hack/probe.py catalog): strip the
    REAL ``with self._lock:`` from ShardSet.manage — a shipped
    guarded-attribute access — and L119 must fire."""
    results = probe.run_all(["guard-strip-shardset"])
    assert results and all(r.ok for r in results), results


def test_l120_seeded_declaration_strip_from_shipped_informer_caught():
    """Strip a shipped ``# guarded-by:`` declaration from the informer
    (a thread-spawning class) and L120 must fire."""
    results = probe.run_all(["declaration-strip-informer"])
    assert results and all(r.ok for r in results), results


# ---------------------------------------------------------------------------
# Probe catalog meta-tests: every contract stays probed (ISSUE 17)
# ---------------------------------------------------------------------------

def _documented_rules():
    """Rule codes documented in docs/static-analysis.md (L1xx rows)."""
    doc = pathlib.Path(ROOT_DIR) / "docs" / "static-analysis.md"
    return sorted(set(re.findall(r"^\| (L1\d\d) \|", doc.read_text(),
                                 flags=re.MULTILINE)))


def test_meta_every_documented_rule_has_fixture_pair():
    """Every documented rule L101-L120 ships a firing AND a clean
    fixture under tests/lint_fixtures/ — a future rule cannot land
    without both."""
    rules = _documented_rules()
    assert rules, "no rules parsed from docs/static-analysis.md"
    assert rules[0] == "L101" and rules[-1] == "L120", rules
    for rule in rules:
        prefix = rule.lower() + "_"
        fixtures = sorted(FIXTURES.glob(prefix + "*.py"))
        assert len(fixtures) >= 2, \
            f"{rule}: needs a firing+clean fixture pair, " \
            f"found {[f.name for f in fixtures]}"


def test_meta_every_documented_rule_has_registered_probe():
    """Every documented rule has a contract-mutation probe in the
    hack/probe.py catalog — the lint gate cannot grow a rule whose
    checker is never proven to fire."""
    rules = _documented_rules()
    probed = {p.rule for p in probe.PROBES}
    missing = [r for r in rules if r not in probed]
    assert not missing, f"rules without a registered probe: {missing}"


def test_probe_catalog_all_fire():
    """The full catalog run: every registered strip-the-contract
    mutation fires its rule against the real tree (what ``make
    probes`` enforces in CI)."""
    results = probe.run_all()
    failed = [r for r in results if not r.ok]
    assert not failed, [(r.probe.name, r.detail) for r in failed]
