"""Weight policies: the TPU planner wired into the binding controller.

StaticWeightPolicy is reference parity (spec.weight everywhere,
reconcile.go:197-204); ModelWeightPolicy plans a full 255-budget
allocation for ``spec.weight: null`` bindings.  The churn-safety
contract (features are a pure function of durable identity) is what
keeps the level-triggered reconcile loop quiescent — tested both at the
policy level and through a running control plane.
"""

from aws_global_accelerator_controller_tpu.apis.endpointgroupbinding.v1alpha1 import (  # noqa: E501
    EndpointGroupBinding,
    EndpointGroupBindingSpec,
    ServiceReference,
)
from aws_global_accelerator_controller_tpu.cloudprovider.aws.types import (
    EndpointGroup,
)
from aws_global_accelerator_controller_tpu.controller.weightpolicy import (
    ModelWeightPolicy,
    StaticWeightPolicy,
    make_weight_policy,
)
from aws_global_accelerator_controller_tpu.kube.objects import ObjectMeta

from harness import Cluster, wait_until

EG_ARN = ("arn:aws:globalaccelerator::123456789012:accelerator/a"
          "/listener/l/endpoint-group/eg1")
LB = ("arn:aws:elasticloadbalancing:us-east-1:123456789012:"
      "loadbalancer/net/one/aaa")
LB2 = ("arn:aws:elasticloadbalancing:us-east-1:123456789012:"
       "loadbalancer/net/two/bbb")


def _binding(weight=None, eg_arn=EG_ARN):
    return EndpointGroupBinding(
        metadata=ObjectMeta(name="b", namespace="default"),
        spec=EndpointGroupBindingSpec(
            endpoint_group_arn=eg_arn, weight=weight,
            service_ref=ServiceReference(name="app")))


def _eg():
    return EndpointGroup(endpoint_group_arn=EG_ARN)


def test_static_policy_reference_parity():
    policy = StaticWeightPolicy()
    assert policy.plan(_binding(64), _eg(), [LB, LB2]) == {LB: 64,
                                                          LB2: 64}
    assert policy.plan(_binding(None), _eg(), [LB]) == {LB: None}


def test_model_policy_defers_to_explicit_spec_weight():
    policy = ModelWeightPolicy()
    assert policy.plan(_binding(7), _eg(), [LB, LB2]) == {LB: 7, LB2: 7}


def test_model_policy_plans_full_budget_deterministically():
    policy = ModelWeightPolicy()
    got = policy.plan(_binding(None), _eg(), [LB, LB2])
    assert set(got) == {LB, LB2}
    assert all(isinstance(w, int) and 0 <= w <= 255
               for w in got.values())
    # full-budget allocation (integer rounding slack <= E)
    assert abs(sum(got.values()) - 255) <= 2
    # churn safety: identical inputs -> identical plan, across
    # instances (fresh params from the same deterministic seed)
    assert policy.plan(_binding(None), _eg(), [LB, LB2]) == got
    assert ModelWeightPolicy().plan(_binding(None), _eg(),
                                    [LB, LB2]) == got


def test_model_policy_empty_group():
    assert ModelWeightPolicy().plan(_binding(None), _eg(), []) == {}


def test_make_weight_policy():
    import pytest

    assert isinstance(make_weight_policy("static"), StaticWeightPolicy)
    assert isinstance(make_weight_policy("model"), ModelWeightPolicy)
    with pytest.raises(ValueError):
        make_weight_policy("llm")


def test_model_policy_through_running_control_plane():
    """e2e: a spec.weight: null binding converges to model-planned
    weights in the fake cloud and stays stable across reconciles."""
    cluster = Cluster(weight_policy="model").start()
    try:
        region = "us-east-1"
        host = f"app-0123456789abcdef.elb.{region}.amazonaws.com"
        cluster.cloud.elb.register_load_balancer("app", host, region)
        # accelerator chain made out-of-band, the binding controller's
        # normal situation (same shape as test_e2e_endpointgroupbinding)
        ga = cluster.cloud.ga
        acc = ga.create_accelerator("ext", "IPV4", True, {})
        from aws_global_accelerator_controller_tpu.cloudprovider.aws.types import (  # noqa: E501
            PortRange,
        )
        listener = ga.create_listener(acc.accelerator_arn,
                                      [PortRange(80, 80)], "TCP", "NONE")
        seed_lb = cluster.cloud.elb.register_load_balancer(
            "seed", f"seed-0123456789abcdef.elb.{region}.amazonaws.com",
            region)
        eg = ga.create_endpoint_group(listener.listener_arn, region,
                                      seed_lb.load_balancer_arn, False)
        eg_arn = eg.endpoint_group_arn

        from aws_global_accelerator_controller_tpu.kube.objects import (
            LoadBalancerIngress,
            LoadBalancerStatus,
            Service,
            ServicePort,
            ServiceSpec,
            ServiceStatus,
        )
        cluster.kube.services.create(Service(
            metadata=ObjectMeta(name="app", namespace="default"),
            spec=ServiceSpec(type="LoadBalancer",
                             ports=[ServicePort(port=80)]),
            status=ServiceStatus(load_balancer=LoadBalancerStatus(
                ingress=[LoadBalancerIngress(hostname=host)]))))
        cluster.operator.endpoint_group_bindings.create(
            _binding(None, eg_arn))

        def app_weight():
            eps = cluster.cloud.ga.describe_endpoint_group(
                eg_arn).endpoint_descriptions
            for ep in eps:
                if "loadbalancer/net/app/" in (ep.endpoint_id or ""):
                    return ep.weight
            return None

        wait_until(lambda: app_weight() is not None, timeout=30.0,
                   message="model-planned weight applied")
        first = app_weight()
        assert 0 <= first <= 255

        # spec.weight round-trip: explicit weight wins (reference
        # semantics), and returning to null REPLANS to the identical
        # model weight — determinism through the running controller
        binding = cluster.operator.endpoint_group_bindings.get(
            "default", "b")
        binding.spec.weight = 128
        cluster.operator.endpoint_group_bindings.update(binding)
        wait_until(lambda: app_weight() == 128, timeout=30.0,
                   message="explicit spec.weight applied")
        binding = cluster.operator.endpoint_group_bindings.get(
            "default", "b")
        binding.spec.weight = None
        cluster.operator.endpoint_group_bindings.update(binding)
        wait_until(lambda: app_weight() == first, timeout=30.0,
                   message="model replanned to the identical weight")
    finally:
        cluster.shutdown()
