"""Weight policies: the TPU planner wired into the binding controller.

StaticWeightPolicy is reference parity (spec.weight everywhere,
reconcile.go:197-204); ModelWeightPolicy plans a full 255-budget
allocation for ``spec.weight: null`` bindings.  The churn-safety
contract (features are a pure function of durable identity) is what
keeps the level-triggered reconcile loop quiescent — tested both at the
policy level and through a running control plane.
"""

from aws_global_accelerator_controller_tpu.apis.endpointgroupbinding.v1alpha1 import (  # noqa: E501
    EndpointGroupBinding,
    EndpointGroupBindingSpec,
    ServiceReference,
)
from aws_global_accelerator_controller_tpu.cloudprovider.aws.types import (
    EndpointGroup,
)
from aws_global_accelerator_controller_tpu.controller.weightpolicy import (
    ModelWeightPolicy,
    StaticWeightPolicy,
    make_weight_policy,
)
from aws_global_accelerator_controller_tpu.kube.objects import ObjectMeta

from harness import Cluster, wait_until

EG_ARN = ("arn:aws:globalaccelerator::123456789012:accelerator/a"
          "/listener/l/endpoint-group/eg1")
LB = ("arn:aws:elasticloadbalancing:us-east-1:123456789012:"
      "loadbalancer/net/one/aaa")
LB2 = ("arn:aws:elasticloadbalancing:us-east-1:123456789012:"
       "loadbalancer/net/two/bbb")


def _binding(weight=None, eg_arn=EG_ARN):
    return EndpointGroupBinding(
        metadata=ObjectMeta(name="b", namespace="default"),
        spec=EndpointGroupBindingSpec(
            endpoint_group_arn=eg_arn, weight=weight,
            service_ref=ServiceReference(name="app")))


def _eg():
    return EndpointGroup(endpoint_group_arn=EG_ARN)


def test_static_policy_reference_parity():
    policy = StaticWeightPolicy()
    assert policy.plan(_binding(64), _eg(), [LB, LB2]) == {LB: 64,
                                                          LB2: 64}
    assert policy.plan(_binding(None), _eg(), [LB]) == {LB: None}


def test_model_policy_defers_to_explicit_spec_weight():
    policy = ModelWeightPolicy()
    assert policy.plan(_binding(7), _eg(), [LB, LB2]) == {LB: 7, LB2: 7}


def test_model_policy_plans_full_budget_deterministically():
    policy = ModelWeightPolicy()
    got = policy.plan(_binding(None), _eg(), [LB, LB2])
    assert set(got) == {LB, LB2}
    assert all(isinstance(w, int) and 0 <= w <= 255
               for w in got.values())
    # full-budget allocation (integer rounding slack <= E)
    assert abs(sum(got.values()) - 255) <= 2
    # churn safety: identical inputs -> identical plan, across
    # instances (fresh params from the same deterministic seed)
    assert policy.plan(_binding(None), _eg(), [LB, LB2]) == got
    assert ModelWeightPolicy().plan(_binding(None), _eg(),
                                    [LB, LB2]) == got


def test_model_policy_empty_group():
    assert ModelWeightPolicy().plan(_binding(None), _eg(), []) == {}


def test_make_weight_policy():
    import pytest

    assert isinstance(make_weight_policy("static"), StaticWeightPolicy)
    assert isinstance(make_weight_policy("model"), ModelWeightPolicy)
    with pytest.raises(ValueError):
        make_weight_policy("llm")


def test_model_policy_through_running_control_plane():
    """e2e: a spec.weight: null binding converges to model-planned
    weights in the fake cloud and stays stable across reconciles."""
    cluster = Cluster(weight_policy="model").start()
    try:
        region = "us-east-1"
        host = f"app-0123456789abcdef.elb.{region}.amazonaws.com"
        cluster.cloud.elb.register_load_balancer("app", host, region)
        # accelerator chain made out-of-band, the binding controller's
        # normal situation (same shape as test_e2e_endpointgroupbinding)
        ga = cluster.cloud.ga
        acc = ga.create_accelerator("ext", "IPV4", True, {})
        from aws_global_accelerator_controller_tpu.cloudprovider.aws.types import (  # noqa: E501
            PortRange,
        )
        listener = ga.create_listener(acc.accelerator_arn,
                                      [PortRange(80, 80)], "TCP", "NONE")
        seed_lb = cluster.cloud.elb.register_load_balancer(
            "seed", f"seed-0123456789abcdef.elb.{region}.amazonaws.com",
            region)
        eg = ga.create_endpoint_group(listener.listener_arn, region,
                                      seed_lb.load_balancer_arn, False)
        eg_arn = eg.endpoint_group_arn

        from aws_global_accelerator_controller_tpu.kube.objects import (
            LoadBalancerIngress,
            LoadBalancerStatus,
            Service,
            ServicePort,
            ServiceSpec,
            ServiceStatus,
        )
        cluster.kube.services.create(Service(
            metadata=ObjectMeta(name="app", namespace="default"),
            spec=ServiceSpec(type="LoadBalancer",
                             ports=[ServicePort(port=80)]),
            status=ServiceStatus(load_balancer=LoadBalancerStatus(
                ingress=[LoadBalancerIngress(hostname=host)]))))
        cluster.operator.endpoint_group_bindings.create(
            _binding(None, eg_arn))

        def app_weight():
            eps = cluster.cloud.ga.describe_endpoint_group(
                eg_arn).endpoint_descriptions
            for ep in eps:
                if "loadbalancer/net/app/" in (ep.endpoint_id or ""):
                    return ep.weight
            return None

        wait_until(lambda: app_weight() is not None, timeout=30.0,
                   message="model-planned weight applied")
        first = app_weight()
        assert 0 <= first <= 255

        # spec.weight round-trip: explicit weight wins (reference
        # semantics), and returning to null REPLANS to the identical
        # model weight — determinism through the running controller
        binding = cluster.operator.endpoint_group_bindings.get(
            "default", "b")
        binding.spec.weight = 128
        cluster.operator.endpoint_group_bindings.update(binding)
        wait_until(lambda: app_weight() == 128, timeout=30.0,
                   message="explicit spec.weight applied")
        binding = cluster.operator.endpoint_group_bindings.get(
            "default", "b")
        binding.spec.weight = None
        cluster.operator.endpoint_group_bindings.update(binding)
        wait_until(lambda: app_weight() == first, timeout=30.0,
                   message="model replanned to the identical weight")
    finally:
        cluster.shutdown()


# -- trained-checkpoint policy (VERDICT r2 weak #5) -------------------------


import os
import subprocess
import sys

import pytest


def _train_cli(ckpt_dir, steps=50, hidden=None):
    """Train via the real CLI (subprocess), saving orbax checkpoints —
    the same artifact a user's `train --ckpt` run produces."""
    cmd = [sys.executable, "-m", "aws_global_accelerator_controller_tpu",
           "train", "--model", "mlp", "--steps", str(steps),
           "--groups", "32", "--endpoints", "8",
           "--ckpt", str(ckpt_dir)]
    if hidden is not None:
        cmd += ["--hidden", str(hidden)]
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(cmd, capture_output=True, text=True,
                          timeout=300, env=env,
                          cwd=os.path.dirname(os.path.dirname(
                              os.path.abspath(__file__))))
    assert proc.returncode == 0, proc.stderr[-2000:]


@pytest.fixture(scope="module")
def trained_ckpt(tmp_path_factory):
    d = tmp_path_factory.mktemp("policy-ckpt")
    _train_cli(d)
    return str(d)


def test_from_checkpoint_plans_trained_weights(trained_ckpt):
    """The trained policy (a) actually loads the CLI's checkpoint,
    (b) plans different weights than the seed-0 init — the checkpoint
    demonstrably drives production weight decisions, (c) stays
    deterministic across reconciles, and (d) still defers to an
    explicit spec.weight (reference semantics)."""
    trained = ModelWeightPolicy.from_checkpoint(trained_ckpt)
    assert trained.restored_step == 50
    seed0 = ModelWeightPolicy()

    binding, eg = _binding(None), _eg()
    ids = [LB, LB2]
    plan_trained = trained.plan(binding, eg, ids)
    plan_seed0 = seed0.plan(binding, eg, ids)
    assert plan_trained != plan_seed0, (
        "50 optimizer steps left the planned weights identical to the "
        "untrained init — the checkpoint is not reaching the policy")
    # churn safety survives the restore: replanning is bit-identical
    assert trained.plan(binding, eg, ids) == plan_trained
    # explicit spec.weight wins exactly as with the untrained policy
    assert trained.plan(_binding(9), eg, ids) == {LB: 9, LB2: 9}


def test_from_checkpoint_failure_modes(tmp_path):
    # missing checkpoint: loud, not silent seed-0 fallback
    with pytest.raises(FileNotFoundError):
        ModelWeightPolicy.from_checkpoint(str(tmp_path / "empty"))
    # static policy + checkpoint dir is a config contradiction
    with pytest.raises(ValueError, match="model"):
        make_weight_policy("static", "/some/ckpt")


def test_from_checkpoint_config_mismatch_is_loud(tmp_path):
    """A checkpoint trained at a different hidden width must raise a
    ValueError naming the config, not restore garbage."""
    d = tmp_path / "h64"
    _train_cli(d, steps=2, hidden=64)
    with pytest.raises(ValueError, match="hidden_dim"):
        ModelWeightPolicy.from_checkpoint(str(d))
    # and the same checkpoint loads fine when the config matches
    ModelWeightPolicy.from_checkpoint(str(d), hidden_dim=64)


def test_controller_cli_rejects_checkpoint_without_model_policy():
    from aws_global_accelerator_controller_tpu.cmd.root import (
        build_parser,
        run_controller,
    )

    args = build_parser().parse_args(
        ["controller", "--policy-checkpoint", "/x"])
    with pytest.raises(SystemExit, match="weight-policy model"):
        run_controller(args)


def test_trained_policy_through_running_control_plane(trained_ckpt):
    """Full e2e: train CLI checkpoint -> controller config -> the fake
    cloud converges to the TRAINED plan (differing from seed-0's) and
    holds it across reconciles."""
    region = "us-east-1"
    trained_plan = ModelWeightPolicy.from_checkpoint(trained_ckpt)
    seed0_plan = ModelWeightPolicy()

    cluster = Cluster(weight_policy="model",
                      policy_checkpoint=trained_ckpt).start()
    try:
        host = f"app-0123456789abcdef.elb.{region}.amazonaws.com"
        cluster.cloud.elb.register_load_balancer("app", host, region)
        ga = cluster.cloud.ga
        acc = ga.create_accelerator("ext", "IPV4", True, {})
        from aws_global_accelerator_controller_tpu.cloudprovider.aws.types import (  # noqa: E501
            PortRange,
        )
        listener = ga.create_listener(acc.accelerator_arn,
                                      [PortRange(80, 80)], "TCP", "NONE")
        seed_lb = cluster.cloud.elb.register_load_balancer(
            "seed", f"seed-0123456789abcdef.elb.{region}.amazonaws.com",
            region)
        eg = ga.create_endpoint_group(listener.listener_arn, region,
                                      seed_lb.load_balancer_arn, False)
        eg_arn = eg.endpoint_group_arn

        from aws_global_accelerator_controller_tpu.kube.objects import (
            LoadBalancerIngress,
            LoadBalancerStatus,
            Service,
            ServicePort,
            ServiceSpec,
            ServiceStatus,
        )
        cluster.kube.services.create(Service(
            metadata=ObjectMeta(name="app", namespace="default"),
            spec=ServiceSpec(type="LoadBalancer",
                             ports=[ServicePort(port=80)]),
            status=ServiceStatus(load_balancer=LoadBalancerStatus(
                ingress=[LoadBalancerIngress(hostname=host)]))))
        cluster.operator.endpoint_group_bindings.create(
            _binding(None, eg_arn))

        def app_endpoint():
            eps = cluster.cloud.ga.describe_endpoint_group(
                eg_arn).endpoint_descriptions
            for ep in eps:
                if "loadbalancer/net/app/" in (ep.endpoint_id or ""):
                    return ep
            return None

        def planned_weight():
            ep = app_endpoint()
            return ep.weight if ep is not None else None

        wait_until(lambda: planned_weight() is not None, timeout=30.0,
                   message="model-planned weight applied")
        ep = app_endpoint()
        want = trained_plan.plan(_binding(None, eg_arn), _eg(),
                                 [ep.endpoint_id])[ep.endpoint_id]
        unwanted = seed0_plan.plan(_binding(None, eg_arn), _eg(),
                                   [ep.endpoint_id])[ep.endpoint_id]
        assert ep.weight == want, (
            "cloud weight is not the trained policy's plan")
        if want != unwanted:
            assert ep.weight != unwanted
    finally:
        cluster.shutdown()


def test_from_checkpoint_missing_dir_leaves_no_litter(tmp_path):
    """A typo'd --policy-checkpoint path must not mkdir an empty orbax
    tree as a side effect of failing."""
    target = tmp_path / "polcy"  # typo'd path
    with pytest.raises(FileNotFoundError):
        ModelWeightPolicy.from_checkpoint(str(target))
    assert not target.exists()


# -- checkpoint hot reload (round 4: the train->serve loop closes) ----------


def _save_policy_step(directory, step, scale=1.0):
    """Write one orbax step the way a retraining Job would, with
    ``scale`` perturbing the params so successive steps plan
    observably different weights."""
    import jax

    from aws_global_accelerator_controller_tpu.controller.weightpolicy import (  # noqa: E501
        FEATURE_DIM,
    )
    from aws_global_accelerator_controller_tpu.models.checkpoint import (
        TrainCheckpointer,
    )
    from aws_global_accelerator_controller_tpu.models.traffic import (
        TrafficPolicyModel,
    )

    model = TrafficPolicyModel(feature_dim=FEATURE_DIM)
    params = model.init_params(jax.random.PRNGKey(1))
    params = jax.tree_util.tree_map(lambda x: x * scale, params)
    with TrainCheckpointer(str(directory)) as ckpt:
        ckpt.save(step, params, model.init_opt_state(params), wait=True)


def test_reloading_policy_swaps_on_new_step(tmp_path):
    """A new checkpoint step written while the controller runs swaps
    into the serving policy (poll driven deterministically via
    poll_once); plans change accordingly and the step is visible."""
    from aws_global_accelerator_controller_tpu.controller.weightpolicy import (  # noqa: E501
        ReloadingModelWeightPolicy,
    )

    d = tmp_path / "ckpt"
    _save_policy_step(d, 1, scale=1.0)
    policy = ReloadingModelWeightPolicy(str(d), interval_s=3600.0)
    try:
        assert policy.restored_step == 1
        before = policy.plan(_binding(None), _eg(), [LB, LB2])
        # no new step yet: poll is a no-op
        assert policy.poll_once() is False
        assert policy.restored_step == 1

        _save_policy_step(d, 2, scale=4.0)
        assert policy.poll_once() is True
        assert policy.restored_step == 2
        after = policy.plan(_binding(None), _eg(), [LB, LB2])
        assert after != before, (
            "retrained params did not reach the serving plan")
        # explicit spec.weight still wins after a reload
        assert policy.plan(_binding(7), _eg(), [LB, LB2]) == {
            LB: 7, LB2: 7}
    finally:
        policy.close()


def test_reloading_policy_keeps_serving_on_bad_reload(tmp_path):
    """A reload failure (config-mismatched retrain) must keep the
    good weights serving and count an error — a training bug must
    never take down a healthy control plane."""
    from aws_global_accelerator_controller_tpu.controller.weightpolicy import (  # noqa: E501
        ReloadingModelWeightPolicy,
    )

    d = tmp_path / "ckpt"
    _save_policy_step(d, 1)
    policy = ReloadingModelWeightPolicy(str(d), interval_s=3600.0)
    try:
        before = policy.plan(_binding(None), _eg(), [LB, LB2])
        # a wrong-width retrain lands as step 2 (hidden_dim != default)
        import jax

        from aws_global_accelerator_controller_tpu.controller.weightpolicy import (  # noqa: E501
            FEATURE_DIM,
        )
        from aws_global_accelerator_controller_tpu.models.checkpoint import (  # noqa: E501
            TrainCheckpointer,
        )
        from aws_global_accelerator_controller_tpu.models.traffic import (
            TrafficPolicyModel,
        )
        wrong = TrafficPolicyModel(feature_dim=FEATURE_DIM,
                                   hidden_dim=64)
        params = wrong.init_params(jax.random.PRNGKey(2))
        with TrainCheckpointer(str(d)) as ckpt:
            ckpt.save(2, params, wrong.init_opt_state(params),
                      wait=True)

        import aws_global_accelerator_controller_tpu.metrics as metrics

        counted = []
        orig = metrics.record_policy_reload
        metrics.record_policy_reload = (
            lambda outcome, registry=None: counted.append(outcome))
        try:
            assert policy.poll_once() is False
        finally:
            metrics.record_policy_reload = orig
        assert counted == ["error"]
        assert policy.restored_step == 1
        assert policy.plan(_binding(None), _eg(), [LB, LB2]) == before
    finally:
        policy.close()


def test_reloading_policy_background_thread_reloads(tmp_path):
    """The real thread path: a short interval picks up a new step
    without any explicit poll, and close() joins the thread."""
    from aws_global_accelerator_controller_tpu.controller.weightpolicy import (  # noqa: E501
        ReloadingModelWeightPolicy,
    )

    d = tmp_path / "ckpt"
    _save_policy_step(d, 1)
    policy = ReloadingModelWeightPolicy(str(d), interval_s=0.2)
    try:
        _save_policy_step(d, 5, scale=3.0)
        wait_until(lambda: policy.restored_step == 5, timeout=30.0,
                   message="background reload picked up step 5")
    finally:
        policy.close()
    assert not policy._thread.is_alive()


def test_reloading_policy_rejects_bad_interval(tmp_path):
    """Non-positive intervals fail at construction (the CLI maps this
    to its own --policy-reload-seconds message before reaching here)."""
    from aws_global_accelerator_controller_tpu.controller.weightpolicy import (  # noqa: E501
        ReloadingModelWeightPolicy,
    )

    d = tmp_path / "ckpt"
    _save_policy_step(d, 1)
    with pytest.raises(ValueError, match="interval"):
        ReloadingModelWeightPolicy(str(d), interval_s=0.0)


def _controller_cli(*extra):
    return subprocess.run(
        [sys.executable, "-m",
         "aws_global_accelerator_controller_tpu", "controller",
         "--fake", "--weight-policy", "model", *extra],
        capture_output=True, text=True, timeout=120,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def test_controller_cli_rejects_reload_without_checkpoint():
    proc = _controller_cli("--policy-reload-seconds", "30")
    assert proc.returncode != 0
    assert "--policy-checkpoint" in proc.stderr


def test_controller_cli_rejects_negative_reload_interval():
    """The error blames the interval flag, not --policy-checkpoint."""
    proc = _controller_cli("--policy-checkpoint", "/nonexistent",
                           "--policy-reload-seconds", "-5")
    assert proc.returncode != 0
    assert "--policy-reload-seconds" in proc.stderr


def test_plan_source_classification(tmp_path):
    """weight_plans_total's source label: a hot-reloading policy is a
    model source exactly like the direct one (dashboards keyed on
    source="model" must not read zero when reload is enabled)."""
    from aws_global_accelerator_controller_tpu.controller.weightpolicy import (  # noqa: E501
        ReloadingModelWeightPolicy,
        plan_source,
    )

    static = StaticWeightPolicy()
    model = ModelWeightPolicy()
    assert plan_source(static, 7) == "spec"
    assert plan_source(model, 7) == "spec"
    assert plan_source(static, None) == "default"
    assert plan_source(model, None) == "model"

    d = tmp_path / "ckpt"
    _save_policy_step(d, 1)
    reloading = ReloadingModelWeightPolicy(str(d), interval_s=3600.0)
    try:
        assert plan_source(reloading, None) == "model"
        assert plan_source(reloading, 3) == "spec"
    finally:
        reloading.close()
