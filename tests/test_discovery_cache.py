"""Ownership-discovery cache: steady-state syncs skip the full tag scan
but every hit is verified, and out-of-band drift falls back to the scan.

The reference rescans the whole fleet (ListAccelerators + per-ARN
ListTags) on EVERY sync (global_accelerator.go:87-110); this rebuild
keeps that as the slow path and serves repeats from a verified,
TTL-bounded cache (provider.py DISCOVERY_CACHE_TTL).
"""
import pytest

from aws_global_accelerator_controller_tpu.cloudprovider.aws.factory import (
    FakeCloudFactory,
)
from aws_global_accelerator_controller_tpu.cloudprovider.aws.helpers import (
    CLUSTER_TAG_KEY,
    MANAGED_TAG_KEY,
)
from aws_global_accelerator_controller_tpu.kube.objects import (
    LoadBalancerIngress,
    ObjectMeta,
    Service,
    ServicePort,
    ServiceSpec,
)

HOSTNAME = "mylb-0123456789abcdef.elb.ap-northeast-1.amazonaws.com"
REGION = "ap-northeast-1"
CLUSTER = "test-cluster"


class CountingGA:
    """Delegating proxy that counts fake GA API calls."""

    def __init__(self, inner):
        self._inner = inner
        self.calls = {}

    def __getattr__(self, name):
        attr = getattr(self._inner, name)
        if not callable(attr):
            return attr

        def counted(*args, **kwargs):
            self.calls[name] = self.calls.get(name, 0) + 1
            return attr(*args, **kwargs)
        return counted


@pytest.fixture
def env():
    factory = FakeCloudFactory(settle_seconds=0.0)
    provider = factory.provider_for(REGION)
    counting = CountingGA(provider.apis.ga)
    provider.apis.ga = counting
    factory.cloud.elb.register_load_balancer("mylb", HOSTNAME, REGION)
    return factory, provider, counting


def _service():
    return Service(
        metadata=ObjectMeta(name="app", namespace="default"),
        spec=ServiceSpec(type="LoadBalancer",
                         ports=[ServicePort(port=80)]),
    )


def _ensure(provider):
    return provider.ensure_global_accelerator_for_service(
        _service(), LoadBalancerIngress(hostname=HOSTNAME), CLUSTER,
        "mylb", REGION)


def test_steady_state_syncs_skip_full_scan(env):
    _, provider, ga = env
    arn, created, _ = _ensure(provider)
    assert created
    scans_after_create = ga.calls.get("list_accelerators", 0)
    for _ in range(5):
        arn2, created2, _ = _ensure(provider)
        assert arn2 == arn and not created2
    # the 5 re-syncs were served by the primed cache: no new full scans
    assert ga.calls["list_accelerators"] == scans_after_create
    # ...but each hit was verified against the live API
    assert ga.calls["describe_accelerator"] >= 5


def test_out_of_band_delete_falls_back_to_scan_and_recreates(env):
    factory, provider, ga = env
    arn, _, _ = _ensure(provider)
    with factory.cloud.ga._lock:  # out-of-band: yank fake state directly
        del factory.cloud.ga._accelerators[arn]
    before = ga.calls.get("list_accelerators", 0)
    arn2, created, _ = _ensure(provider)
    assert created and arn2 != arn
    assert ga.calls["list_accelerators"] > before


def test_out_of_band_tag_strip_invalidates_hit(env):
    factory, provider, ga = env
    arn, _, _ = _ensure(provider)
    # strip the owner tag behind the controller's back
    with factory.cloud.ga._lock:
        factory.cloud.ga._accelerators[arn].tags = {
            MANAGED_TAG_KEY: "true", CLUSTER_TAG_KEY: CLUSTER}
    accs = provider.list_global_accelerator_by_resource(
        CLUSTER, "service", "default", "app")
    # verified hit fails the tag match -> full rescan finds nothing
    assert accs == []


def test_tag_strip_not_masked_by_warm_tag_cache(env):
    """Even when a prior full scan populated the per-ARN tag cache, a
    verified-hit mismatch must not let the fallback scan re-match the
    accelerator through 30s-stale cached tags: the verify path writes
    the fresh tags through before falling back."""
    factory, provider, ga = env
    arn, _, _ = _ensure(provider)
    # a full scan for an unrelated hostname warms _tags_cache with the
    # CURRENT (owned) tags of our accelerator
    assert provider.list_global_accelerator_by_hostname(
        "other.elb.amazonaws.com", CLUSTER) == []
    with factory.cloud.ga._lock:  # out-of-band ownership release
        factory.cloud.ga._accelerators[arn].tags = {
            MANAGED_TAG_KEY: "true", CLUSTER_TAG_KEY: CLUSTER}
    accs = provider.list_global_accelerator_by_resource(
        CLUSTER, "service", "default", "app")
    assert accs == []


def test_failed_verify_rescans_other_accelerators_with_fresh_tags(env):
    """After a verified-hit mismatch, the rescue scan must re-read EVERY
    accelerator's tags from the API, not serve them from the warm tag
    cache — otherwise ownership that moved to another accelerator
    out-of-band stays invisible for up to 2x TTL (ADVICE r1)."""
    factory, provider, ga = env
    arn, _, _ = _ensure(provider)
    owner_tags = dict(factory.cloud.ga.list_tags_for_resource(arn))
    rogue = factory.cloud.ga.create_accelerator(
        name="rogue", ip_address_type="IPV4", enabled=True,
        tags={MANAGED_TAG_KEY: "true", CLUSTER_TAG_KEY: CLUSTER})
    # warm _tags_cache for BOTH accelerators via an unrelated full scan
    assert provider.list_global_accelerator_by_hostname(
        "other.elb.amazonaws.com", CLUSTER) == []
    # out-of-band: ownership moves from arn to rogue
    with factory.cloud.ga._lock:
        factory.cloud.ga._accelerators[arn].tags = {
            MANAGED_TAG_KEY: "true", CLUSTER_TAG_KEY: CLUSTER}
        factory.cloud.ga._accelerators[
            rogue.accelerator_arn].tags = owner_tags
    accs = provider.list_global_accelerator_by_resource(
        CLUSTER, "service", "default", "app")
    # the fresh rescan sees the move immediately (no 2x-TTL blind spot)
    assert [a.accelerator_arn for a in accs] == [rogue.accelerator_arn]


def test_duplicate_detected_after_ttl_expiry(env):
    factory, provider, ga = env
    provider.discovery_cache_ttl = 0.0  # force immediate expiry
    arn, _, _ = _ensure(provider)
    owner_tags = factory.cloud.ga.list_tags_for_resource(arn)
    rogue = factory.cloud.ga.create_accelerator(
        name="rogue", ip_address_type="DUAL_STACK", enabled=True,
        tags=dict(owner_tags))
    accs = provider.list_global_accelerator_by_resource(
        CLUSTER, "service", "default", "app")
    assert len(accs) == 2
    assert {a.accelerator_arn for a in accs} == {
        arn, rogue.accelerator_arn}


def test_retag_not_masked_by_fresh_fleet_index(env):
    """Regression (ADVICE r5 medium): ``_update_accelerator`` re-tags an
    accelerator onto NEW owner/hostname discovery keys.  A fleet index
    installed before the re-tag has never seen those keys, and — being
    fresh — would report them definitely-absent for up to TTL + 1m.
    The update must invalidate the index inside the same _cache_lock
    block as its tag-cache drop."""
    factory, provider, ga = env
    arn, _, _ = _ensure(provider)
    # install a fresh fleet index via an unrelated full scan
    assert provider.list_global_accelerator_by_hostname(
        "other.elb.amazonaws.com", CLUSTER) == []
    provider._update_accelerator(
        arn, name="renamed", owner="service/other/name",
        hostname=HOSTNAME, specified_tags={})
    # the NEW owner key must be discoverable immediately, not after TTL
    accs = provider.list_global_accelerator_by_resource(
        CLUSTER, "service", "other", "name")
    assert [a.accelerator_arn for a in accs] == [arn]


def test_tag_update_visible_immediately_via_writethrough(env):
    """A tag change made through the provider invalidates the tag cache,
    so discovery under the NEW owner works without waiting for the TTL."""
    _, provider, ga = env
    arn, _, _ = _ensure(provider)
    provider._update_accelerator(
        arn, name="renamed", owner="service/other/name",
        hostname=HOSTNAME, specified_tags={})
    accs = provider.list_global_accelerator_by_resource(
        CLUSTER, "service", "other", "name")
    assert [a.accelerator_arn for a in accs] == [arn]


# -- churn-proof index maintenance (ISSUE 7: overload resilience) --------


def test_own_delete_keeps_fleet_index_serving(env):
    """Our own committed delete evicts the arn from the fleet index
    surgically (the prime path's mirror): the index stays COMPLETE and
    installed, so neither a re-lookup of the deleted key nor a brand
    new key's ensure pays a fresh O(fleet) rescan.  Previously the
    stale entry's next verify-failure torched the whole index, and
    under sustained churn every sibling's ensure degenerated to a
    full scan serialized behind the singleflight."""
    factory, provider, ga = env
    arn, created, _ = _ensure(provider)
    assert created
    # install a fresh fleet index via an unrelated miss (full scan)
    assert provider.list_global_accelerator_by_hostname(
        "other.elb.amazonaws.com", CLUSTER) == []
    provider.cleanup_global_accelerator(arn)
    scans_before = ga.calls.get("list_accelerators", 0)
    # the deleted key answers definitely-absent from the maintained
    # index — no rescan, no verify of a dead arn
    assert provider.list_global_accelerator_by_resource(
        CLUSTER, "service", "default", "app") == []
    # and a never-seen key is still an O(1) negative
    assert provider.list_global_accelerator_by_resource(
        CLUSTER, "service", "default", "brand-new") == []
    assert ga.calls.get("list_accelerators", 0) == scans_before, \
        "a committed own-delete forced an O(fleet) rescan"


def test_mid_scan_vanished_arn_skipped_not_fatal(env):
    """TOCTOU inside the fleet scan: an accelerator the list returned
    can be deleted (by a concurrent worker) before its per-ARN tag
    read.  The scan must skip that arn — failing the WHOLE sweep would
    error every rider's sync with an accelerator they never cared
    about (under delete churn that poisons a steady stream of
    unrelated keys)."""
    from aws_global_accelerator_controller_tpu.errors import AWSAPIError

    factory, provider, ga = env
    arn, _, _ = _ensure(provider)
    factory.cloud.elb.register_load_balancer(
        "otherlb",
        "otherlb-0123456789abcdef.elb.ap-northeast-1.amazonaws.com",
        REGION)
    other = Service(
        metadata=ObjectMeta(name="other", namespace="default"),
        spec=ServiceSpec(type="LoadBalancer",
                         ports=[ServicePort(port=80)]))
    arn2, created2, _ = provider.ensure_global_accelerator_for_service(
        other, LoadBalancerIngress(
            hostname="otherlb-0123456789abcdef.elb.ap-northeast-1"
                     ".amazonaws.com"),
        CLUSTER, "otherlb", REGION)
    assert created2

    real_ga = ga._inner

    class VanishingTags:
        def __getattr__(self, name):
            attr = getattr(real_ga, name)
            if name != "list_tags_for_resource":
                return attr

            def tags(a):
                if a == arn:
                    raise AWSAPIError(
                        "AcceleratorNotFoundException",
                        f"accelerator {a} not found")
                return attr(a)
            return tags

    provider.apis.ga = VanishingTags()
    # force the rescue-scan shape: drop every cache layer, then look
    # up the OTHER accelerator — the sweep crosses the poisoned arn
    with provider._s.lock:
        provider._s.discovery.clear()
        provider._s.tags.clear()
        provider._invalidate_fleet_locked()
    accs = provider.list_global_accelerator_by_resource(
        CLUSTER, "service", "default", "other")
    assert [a.accelerator_arn for a in accs] == [arn2], \
        "the scan must survive a mid-scan-vanished arn and still " \
        "answer for everyone else"


def test_own_delete_mid_scan_rides_mutation_log(env):
    """A delete landing while a scan is in flight is recorded in the
    ordered mutation log (after any prime for the same arn, so a
    create-then-delete replays as deleted) — the scan is NOT fenced
    out (starving installs under churn) and does NOT re-install the
    dead arn."""
    factory, provider, ga = env
    with provider._s.lock:
        provider._s.scans_inflight += 1   # a sweep is on the wire
    try:
        arn, _, _ = _ensure(provider)    # primes mid-scan
        provider.cleanup_global_accelerator(arn)
        with provider._s.lock:
            log = list(provider._s.prime_log)
            assert ("death", arn) in log, \
                "mid-scan delete must be logged for the install replay"
            primes = [i for i, e in enumerate(log)
                      if e[0] == "prime" and e[2] == arn]
            death = log.index(("death", arn))
            assert all(i < death for i in primes), \
                "the death must replay AFTER the create's primes"
            assert not any(
                arn in arns
                for arns in provider._s.fleet_index.values()), \
                "dead arn still indexed"
    finally:
        with provider._s.lock:
            provider._s.scans_inflight -= 1
            del provider._s.prime_log[:]


def test_own_retag_keeps_index_installed(env):
    """A re-tag re-indexes the arn surgically (old keys evicted, new
    keys inserted from the merged tag set read back) instead of
    torching the index — under sustained update churn the torch kept
    the index permanently uninstallable and every new key's ensure
    paid a synchronous full rescan."""
    factory, provider, ga = env
    arn, _, _ = _ensure(provider)
    # install a fresh index
    assert provider.list_global_accelerator_by_hostname(
        "other.elb.amazonaws.com", CLUSTER) == []
    with provider._s.lock:
        installed_at = provider._s.fleet_at
    assert installed_at is not None
    provider._update_accelerator(
        arn, name="renamed", owner="service/other/name",
        hostname=HOSTNAME, specified_tags={})
    with provider._s.lock:
        assert provider._s.fleet_at == installed_at, \
            "the re-tag invalidated the index instead of re-indexing"
    scans_before = ga.calls.get("list_accelerators", 0)
    # new owner key served by the index (verified hit), no rescan
    accs = provider.list_global_accelerator_by_resource(
        CLUSTER, "service", "other", "name")
    assert [a.accelerator_arn for a in accs] == [arn]
    # the OLD owner key answers definitely-absent without a rescan
    assert provider.list_global_accelerator_by_resource(
        CLUSTER, "service", "default", "app") == []
    assert ga.calls.get("list_accelerators", 0) == scans_before


def test_aging_index_refreshes_in_background(env):
    """Stale-while-revalidate: past ~75% of the TTL, a lookup serving
    from the still-fresh index kicks ONE background rescan so no
    reconcile worker ever blocks on the O(fleet) sweep at hard
    expiry (the mixed-soak's original whole-second p99 tail)."""
    import time

    from harness import wait_until

    factory, provider, ga = env
    provider.discovery_cache_ttl = 0.4
    arn, _, _ = _ensure(provider)
    assert provider.list_global_accelerator_by_hostname(
        "other.elb.amazonaws.com", CLUSTER) == []
    with provider._s.lock:
        first_install = provider._s.fleet_at
    time.sleep(0.32)   # past the refresh fraction, inside the TTL
    # a fresh-index lookup triggers the async refresh
    assert provider.list_global_accelerator_by_hostname(
        "other.elb.amazonaws.com", CLUSTER) == []

    def rewarmed():
        with provider._s.lock:
            return (provider._s.fleet_at is not None
                    and provider._s.fleet_at > first_install)
    wait_until(rewarmed, timeout=5.0,
               message="background refresh re-installed the index")
