"""Ownership-discovery cache: steady-state syncs skip the full tag scan
but every hit is verified, and out-of-band drift falls back to the scan.

The reference rescans the whole fleet (ListAccelerators + per-ARN
ListTags) on EVERY sync (global_accelerator.go:87-110); this rebuild
keeps that as the slow path and serves repeats from a verified,
TTL-bounded cache (provider.py DISCOVERY_CACHE_TTL).
"""
import pytest

from aws_global_accelerator_controller_tpu.cloudprovider.aws.factory import (
    FakeCloudFactory,
)
from aws_global_accelerator_controller_tpu.cloudprovider.aws.helpers import (
    CLUSTER_TAG_KEY,
    MANAGED_TAG_KEY,
)
from aws_global_accelerator_controller_tpu.kube.objects import (
    LoadBalancerIngress,
    ObjectMeta,
    Service,
    ServicePort,
    ServiceSpec,
)

HOSTNAME = "mylb-0123456789abcdef.elb.ap-northeast-1.amazonaws.com"
REGION = "ap-northeast-1"
CLUSTER = "test-cluster"


class CountingGA:
    """Delegating proxy that counts fake GA API calls."""

    def __init__(self, inner):
        self._inner = inner
        self.calls = {}

    def __getattr__(self, name):
        attr = getattr(self._inner, name)
        if not callable(attr):
            return attr

        def counted(*args, **kwargs):
            self.calls[name] = self.calls.get(name, 0) + 1
            return attr(*args, **kwargs)
        return counted


@pytest.fixture
def env():
    factory = FakeCloudFactory(settle_seconds=0.0)
    provider = factory.provider_for(REGION)
    counting = CountingGA(provider.apis.ga)
    provider.apis.ga = counting
    factory.cloud.elb.register_load_balancer("mylb", HOSTNAME, REGION)
    return factory, provider, counting


def _service():
    return Service(
        metadata=ObjectMeta(name="app", namespace="default"),
        spec=ServiceSpec(type="LoadBalancer",
                         ports=[ServicePort(port=80)]),
    )


def _ensure(provider):
    return provider.ensure_global_accelerator_for_service(
        _service(), LoadBalancerIngress(hostname=HOSTNAME), CLUSTER,
        "mylb", REGION)


def test_steady_state_syncs_skip_full_scan(env):
    _, provider, ga = env
    arn, created, _ = _ensure(provider)
    assert created
    scans_after_create = ga.calls.get("list_accelerators", 0)
    for _ in range(5):
        arn2, created2, _ = _ensure(provider)
        assert arn2 == arn and not created2
    # the 5 re-syncs were served by the primed cache: no new full scans
    assert ga.calls["list_accelerators"] == scans_after_create
    # ...but each hit was verified against the live API
    assert ga.calls["describe_accelerator"] >= 5


def test_out_of_band_delete_falls_back_to_scan_and_recreates(env):
    factory, provider, ga = env
    arn, _, _ = _ensure(provider)
    with factory.cloud.ga._lock:  # out-of-band: yank fake state directly
        del factory.cloud.ga._accelerators[arn]
    before = ga.calls.get("list_accelerators", 0)
    arn2, created, _ = _ensure(provider)
    assert created and arn2 != arn
    assert ga.calls["list_accelerators"] > before


def test_out_of_band_tag_strip_invalidates_hit(env):
    factory, provider, ga = env
    arn, _, _ = _ensure(provider)
    # strip the owner tag behind the controller's back
    with factory.cloud.ga._lock:
        factory.cloud.ga._accelerators[arn].tags = {
            MANAGED_TAG_KEY: "true", CLUSTER_TAG_KEY: CLUSTER}
    accs = provider.list_global_accelerator_by_resource(
        CLUSTER, "service", "default", "app")
    # verified hit fails the tag match -> full rescan finds nothing
    assert accs == []


def test_tag_strip_not_masked_by_warm_tag_cache(env):
    """Even when a prior full scan populated the per-ARN tag cache, a
    verified-hit mismatch must not let the fallback scan re-match the
    accelerator through 30s-stale cached tags: the verify path writes
    the fresh tags through before falling back."""
    factory, provider, ga = env
    arn, _, _ = _ensure(provider)
    # a full scan for an unrelated hostname warms _tags_cache with the
    # CURRENT (owned) tags of our accelerator
    assert provider.list_global_accelerator_by_hostname(
        "other.elb.amazonaws.com", CLUSTER) == []
    with factory.cloud.ga._lock:  # out-of-band ownership release
        factory.cloud.ga._accelerators[arn].tags = {
            MANAGED_TAG_KEY: "true", CLUSTER_TAG_KEY: CLUSTER}
    accs = provider.list_global_accelerator_by_resource(
        CLUSTER, "service", "default", "app")
    assert accs == []


def test_failed_verify_rescans_other_accelerators_with_fresh_tags(env):
    """After a verified-hit mismatch, the rescue scan must re-read EVERY
    accelerator's tags from the API, not serve them from the warm tag
    cache — otherwise ownership that moved to another accelerator
    out-of-band stays invisible for up to 2x TTL (ADVICE r1)."""
    factory, provider, ga = env
    arn, _, _ = _ensure(provider)
    owner_tags = dict(factory.cloud.ga.list_tags_for_resource(arn))
    rogue = factory.cloud.ga.create_accelerator(
        name="rogue", ip_address_type="IPV4", enabled=True,
        tags={MANAGED_TAG_KEY: "true", CLUSTER_TAG_KEY: CLUSTER})
    # warm _tags_cache for BOTH accelerators via an unrelated full scan
    assert provider.list_global_accelerator_by_hostname(
        "other.elb.amazonaws.com", CLUSTER) == []
    # out-of-band: ownership moves from arn to rogue
    with factory.cloud.ga._lock:
        factory.cloud.ga._accelerators[arn].tags = {
            MANAGED_TAG_KEY: "true", CLUSTER_TAG_KEY: CLUSTER}
        factory.cloud.ga._accelerators[
            rogue.accelerator_arn].tags = owner_tags
    accs = provider.list_global_accelerator_by_resource(
        CLUSTER, "service", "default", "app")
    # the fresh rescan sees the move immediately (no 2x-TTL blind spot)
    assert [a.accelerator_arn for a in accs] == [rogue.accelerator_arn]


def test_duplicate_detected_after_ttl_expiry(env):
    factory, provider, ga = env
    provider.discovery_cache_ttl = 0.0  # force immediate expiry
    arn, _, _ = _ensure(provider)
    owner_tags = factory.cloud.ga.list_tags_for_resource(arn)
    rogue = factory.cloud.ga.create_accelerator(
        name="rogue", ip_address_type="DUAL_STACK", enabled=True,
        tags=dict(owner_tags))
    accs = provider.list_global_accelerator_by_resource(
        CLUSTER, "service", "default", "app")
    assert len(accs) == 2
    assert {a.accelerator_arn for a in accs} == {
        arn, rogue.accelerator_arn}


def test_retag_not_masked_by_fresh_fleet_index(env):
    """Regression (ADVICE r5 medium): ``_update_accelerator`` re-tags an
    accelerator onto NEW owner/hostname discovery keys.  A fleet index
    installed before the re-tag has never seen those keys, and — being
    fresh — would report them definitely-absent for up to TTL + 1m.
    The update must invalidate the index inside the same _cache_lock
    block as its tag-cache drop."""
    factory, provider, ga = env
    arn, _, _ = _ensure(provider)
    # install a fresh fleet index via an unrelated full scan
    assert provider.list_global_accelerator_by_hostname(
        "other.elb.amazonaws.com", CLUSTER) == []
    provider._update_accelerator(
        arn, name="renamed", owner="service/other/name",
        hostname=HOSTNAME, specified_tags={})
    # the NEW owner key must be discoverable immediately, not after TTL
    accs = provider.list_global_accelerator_by_resource(
        CLUSTER, "service", "other", "name")
    assert [a.accelerator_arn for a in accs] == [arn]


def test_tag_update_visible_immediately_via_writethrough(env):
    """A tag change made through the provider invalidates the tag cache,
    so discovery under the NEW owner works without waiting for the TTL."""
    _, provider, ga = env
    arn, _, _ = _ensure(provider)
    provider._update_accelerator(
        arn, name="renamed", owner="service/other/name",
        hostname=HOSTNAME, specified_tags={})
    accs = provider.list_global_accelerator_by_resource(
        CLUSTER, "service", "other", "name")
    assert [a.accelerator_arn for a in accs] == [arn]
