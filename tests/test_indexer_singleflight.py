"""Informer Indexer + provider singleflight contracts.

The two halves of the indexed-reconcile hot path (ARCHITECTURE.md
"Informer indexes & listers" / "Provider read coalescing"):

- the informer cache is a client-go-style Indexer: registerable index
  functions, O(1) ``by_index`` bucket reads, copy-on-write snapshot
  listers, and a shared-read-only-view ownership contract;
- the AWS provider coalesces identical in-flight reads (singleflight),
  so N workers needing the same verify pair issue ONE upstream call.
"""
import threading
import time

import pytest

from aws_global_accelerator_controller_tpu import metrics
from aws_global_accelerator_controller_tpu.cloudprovider.aws.factory import (
    FakeCloudFactory,
)
from aws_global_accelerator_controller_tpu.cloudprovider.aws.singleflight import (
    Singleflight,
)
from aws_global_accelerator_controller_tpu.kube.apiserver import FakeAPIServer
from aws_global_accelerator_controller_tpu.kube.client import KubeClient
from aws_global_accelerator_controller_tpu.kube.informers import (
    SharedInformerFactory,
    wait_for_cache_sync,
)
from aws_global_accelerator_controller_tpu.kube.objects import (
    LoadBalancerIngress,
    ObjectMeta,
    Service,
    ServicePort,
    ServiceSpec,
)

HOSTNAME = "mylb-0123456789abcdef.elb.ap-northeast-1.amazonaws.com"
REGION = "ap-northeast-1"
CLUSTER = "test-cluster"


def make_service(name, ns="default", team=None):
    ann = {"team": team} if team else {}
    return Service(metadata=ObjectMeta(name=name, namespace=ns,
                                       annotations=ann),
                   spec=ServiceSpec(type="LoadBalancer"))


def wait_until(pred, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.01)
    return False


@pytest.fixture
def informer_env():
    api = FakeAPIServer()
    kube = KubeClient(api)
    factory = SharedInformerFactory(api, resync_period=300)
    informer = factory.services()
    informer.add_index("team", lambda o: (
        [o.metadata.annotations["team"]]
        if "team" in o.metadata.annotations else []))
    stop = threading.Event()
    factory.start(stop)
    assert wait_for_cache_sync(stop, informer, timeout=10.0)
    yield api, kube, informer
    stop.set()


# ---------------------------------------------------------------------------
# Indexer
# ---------------------------------------------------------------------------

def test_by_index_tracks_adds_updates_deletes(informer_env):
    api, kube, informer = informer_env
    kube.services.create(make_service("a", team="red"))
    kube.services.create(make_service("b", team="red"))
    kube.services.create(make_service("c", team="blue"))
    assert wait_until(lambda: len(informer.by_index("team", "red")) == 2)
    assert [o.metadata.name for o in informer.by_index("team", "blue")] == ["c"]
    assert informer.by_index("team", "green") == []

    svc = kube.services.get("default", "b")
    svc.metadata.annotations["team"] = "blue"
    kube.services.update(svc)
    assert wait_until(lambda: len(informer.by_index("team", "blue")) == 2)
    assert [o.metadata.name for o in informer.by_index("team", "red")] == ["a"]

    kube.services.delete("default", "c")
    assert wait_until(lambda: [o.metadata.name
                               for o in informer.by_index("team", "blue")]
                      == ["b"])


def test_add_index_after_sync_rebuilds_over_cache(informer_env):
    api, kube, informer = informer_env
    kube.services.create(make_service("x", team="late"))
    assert wait_until(lambda: informer.cache_get("default/x") is not None)
    # register AFTER the object is cached: index must include it
    informer.add_index("team2", lambda o: (
        [o.metadata.annotations["team"]]
        if "team" in o.metadata.annotations else []))
    assert [o.metadata.name for o in informer.by_index("team2", "late")] == ["x"]


def test_unregistered_index_is_a_programming_error(informer_env):
    _, _, informer = informer_env
    with pytest.raises(KeyError):
        informer.by_index("nope", "value")


def test_namespace_index_backs_namespaced_list(informer_env):
    api, kube, informer = informer_env
    kube.services.create(make_service("n1", ns="alpha"))
    kube.services.create(make_service("n2", ns="beta"))
    assert wait_until(lambda: len(informer.lister.list()) == 2)
    assert [o.metadata.name for o in informer.lister.list("alpha")] == ["n1"]
    assert informer.lister.list("gamma") == []


def test_cow_snapshot_shared_until_mutation(informer_env):
    api, kube, informer = informer_env
    kube.services.create(make_service("s1"))
    assert wait_until(lambda: len(informer.lister.list()) == 1)
    first = informer.lister.list()
    second = informer.lister.list()
    # no mutation between reads: the same cached OBJECTS are served
    # (no per-call deepcopy — the old cache_list cost), but each call
    # gets its own list so callers may sort/mutate the result safely
    assert first[0] is second[0]
    assert first is not second
    second.append(None)      # caller-side mutation stays caller-side
    assert len(informer.lister.list()) == 1


def test_lister_returns_shared_views(informer_env):
    api, kube, informer = informer_env
    kube.services.create(make_service("shared"))
    assert wait_until(lambda: informer.cache_get("default/shared") is not None)
    # get() hands back the cached object itself (read-only contract);
    # the defensive copy belongs to the reconcile engine
    assert (informer.lister.get("default", "shared")
            is informer.lister.get("default", "shared"))


def test_index_lookup_counters_move(informer_env):
    api, kube, informer = informer_env
    kube.services.create(make_service("m", team="metrics"))
    assert wait_until(lambda: len(informer.by_index("team", "metrics")) == 1)
    reg = metrics.default_registry
    hit0 = reg.counter_value("informer_index_lookups_total",
                             {"kind": "Service", "index": "team",
                              "result": "hit"})
    miss0 = reg.counter_value("informer_index_lookups_total",
                              {"kind": "Service", "index": "team",
                               "result": "miss"})
    informer.by_index("team", "metrics")
    informer.by_index("team", "absent")
    assert reg.counter_value("informer_index_lookups_total",
                             {"kind": "Service", "index": "team",
                              "result": "hit"}) == hit0 + 1
    assert reg.counter_value("informer_index_lookups_total",
                             {"kind": "Service", "index": "team",
                              "result": "miss"}) == miss0 + 1


# ---------------------------------------------------------------------------
# Singleflight
# ---------------------------------------------------------------------------

def test_singleflight_n_threads_one_upstream_call():
    coalesced = []
    sf = Singleflight(on_coalesce=coalesced.append)
    calls = []
    barrier = threading.Barrier(8)
    results = []

    def fn():
        calls.append(1)
        time.sleep(0.2)     # hold the call open so every thread joins
        return "value"

    def worker():
        barrier.wait()
        results.append(sf.do("key", fn))

    threads = [threading.Thread(target=worker) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=10)
    assert len(calls) == 1                 # exactly one upstream call
    assert results == ["value"] * 8        # every caller observed it
    assert len(coalesced) == 7             # the other 7 joined


def test_singleflight_exception_shared_by_joiners():
    sf = Singleflight()
    barrier = threading.Barrier(4)
    errors = []

    def fn():
        time.sleep(0.2)
        raise ValueError("boom")

    def worker():
        barrier.wait()
        try:
            sf.do("key", fn)
        except ValueError as e:
            errors.append(str(e))

    threads = [threading.Thread(target=worker) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=10)
    assert errors == ["boom"] * 4


def test_singleflight_does_not_cache_results():
    sf = Singleflight()
    calls = []
    for _ in range(3):
        sf.do("key", lambda: calls.append(1))
    assert len(calls) == 3     # sequential callers each run fresh


# ---------------------------------------------------------------------------
# Provider read coalescing
# ---------------------------------------------------------------------------

def _ensure(provider):
    return provider.ensure_global_accelerator_for_service(
        Service(metadata=ObjectMeta(name="app", namespace="default"),
                spec=ServiceSpec(type="LoadBalancer",
                                 ports=[ServicePort(port=80)])),
        LoadBalancerIngress(hostname=HOSTNAME), CLUSTER, "mylb", REGION)


def test_concurrent_verifies_coalesce_to_one_api_call():
    factory = FakeCloudFactory(settle_seconds=0.0)
    provider = factory.provider_for(REGION)
    factory.cloud.elb.register_load_balancer("mylb", HOSTNAME, REGION)
    arn, created, _ = _ensure(provider)
    assert created

    describe_calls = []
    inner = provider.apis.ga.describe_accelerator

    def slow_describe(a):
        describe_calls.append(a)
        time.sleep(0.2)
        return inner(a)

    provider.apis.ga.describe_accelerator = slow_describe
    reg = metrics.default_registry
    co0 = reg.counter_value("provider_coalesced_reads_total",
                            {"op": "verify"})

    barrier = threading.Barrier(8)
    results = []

    def worker():
        barrier.wait()
        results.append(provider.list_global_accelerator_by_resource(
            CLUSTER, "service", "default", "app"))

    threads = [threading.Thread(target=worker) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=10)

    # every worker hit the hot discovery key at once: ONE
    # DescribeAccelerator upstream, everyone shares the verified result
    assert len(describe_calls) == 1
    assert all([a.accelerator_arn for a in r] == [arn] for r in results)
    assert reg.counter_value("provider_coalesced_reads_total",
                             {"op": "verify"}) == co0 + 7


def test_discovery_state_shared_across_factory_providers():
    """GA is a global service: a create through one region's provider
    must be visible to every other provider of the same factory
    IMMEDIATELY (not after a TTL) — the regression behind the pre-PR
    e2e timeouts, where the us-west-2 provider's fresh-but-empty fleet
    index answered definitely-absent while ap-northeast-1 created."""
    factory = FakeCloudFactory(settle_seconds=0.0)
    observer = factory.global_provider()
    actor = factory.provider_for(REGION)
    assert observer is not actor
    factory.cloud.elb.register_load_balancer("mylb", HOSTNAME, REGION)

    # the observer polls first: installs a fresh EMPTY fleet index
    assert observer.list_global_accelerator_by_resource(
        CLUSTER, "service", "default", "app") == []
    arn, created, _ = _ensure(actor)
    assert created
    # no TTL wait: the shared discovery state makes the create visible
    accs = observer.list_global_accelerator_by_resource(
        CLUSTER, "service", "default", "app")
    assert [a.accelerator_arn for a in accs] == [arn]
