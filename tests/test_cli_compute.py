"""CLI compute track: train (with resume) and plan subcommands."""
import json

import pytest

from aws_global_accelerator_controller_tpu.cmd.root import main


def test_train_checkpoints_and_resumes(tmp_path, capsys):
    ckpt = str(tmp_path / "ckpt")
    assert main(["train", "--steps", "3", "--ckpt", ckpt,
                 "--groups", "8", "--endpoints", "8",
                 "--hidden", "16", "--save-every", "2"]) == 0
    first = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert first["step"] == 3
    assert first["loss"] is not None

    # second invocation resumes from step 3
    assert main(["train", "--steps", "2", "--ckpt", ckpt,
                 "--groups", "8", "--endpoints", "8",
                 "--hidden", "16"]) == 0
    second = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert second["step"] == 5


def test_train_steps_multiple_of_save_every_does_not_crash(tmp_path,
                                                           capsys):
    """Periodic save at the final step + unconditional final save must
    not collide (orbax raises StepAlreadyExistsError on duplicates)."""
    ckpt = str(tmp_path / "ckpt")
    assert main(["train", "--steps", "4", "--ckpt", ckpt,
                 "--groups", "8", "--endpoints", "8",
                 "--hidden", "16", "--save-every", "2"]) == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["step"] == 4


def test_plan_emits_valid_weight_allocations(tmp_path, capsys):
    assert main(["plan", "--groups", "4", "--endpoints", "6",
                 "--hidden", "16"]) == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["groups"] == 4 and out["endpoints"] == 6
    assert len(out["weights"]) == 4
    for row in out["weights"]:
        assert len(row) == 6
        assert all(0 <= w <= 255 for w in row)
        # valid (unmasked) endpoints share ~255 total; padded rows are 0
        assert sum(row) <= 255 + 3  # rounding slack


def test_plan_uses_trained_checkpoint(tmp_path, capsys):
    ckpt = str(tmp_path / "c")
    assert main(["train", "--steps", "2", "--ckpt", ckpt,
                 "--groups", "8", "--endpoints", "8",
                 "--hidden", "16"]) == 0
    capsys.readouterr()
    assert main(["plan", "--ckpt", ckpt, "--groups", "3",
                 "--endpoints", "5", "--hidden", "16"]) == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert len(out["weights"]) == 3


def test_temporal_model_trains_and_plans(tmp_path, capsys):
    ckpt = str(tmp_path / "tck")
    assert main(["train", "--model", "temporal", "--steps", "2",
                 "--ckpt", ckpt, "--groups", "4", "--endpoints", "6",
                 "--hidden", "16", "--window", "4"]) == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["model"] == "temporal" and out["step"] == 2
    assert main(["plan", "--model", "temporal", "--ckpt", ckpt,
                 "--groups", "4", "--endpoints", "6", "--hidden", "16",
                 "--window", "4"]) == 0
    plan = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert len(plan["weights"]) == 4
    assert all(0 <= w <= 255 for row in plan["weights"] for w in row)


def test_sharded_temporal_trains_and_plans(tmp_path, capsys):
    """--sharded builds a data x seq mesh over the 8 virtual CPU
    devices and trains through ring attention."""
    ckpt = str(tmp_path / "sck")
    assert main(["train", "--model", "temporal", "--sharded",
                 "--steps", "2", "--ckpt", ckpt, "--groups", "4",
                 "--endpoints", "4", "--hidden", "16",
                 "--window", "8"]) == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["model"] == "temporal" and out["step"] == 2
    assert main(["plan", "--model", "temporal", "--sharded",
                 "--ckpt", ckpt, "--groups", "4", "--endpoints", "4",
                 "--hidden", "16", "--window", "8"]) == 0
    plan = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert len(plan["weights"]) == 4
    assert all(0 <= w <= 255 for row in plan["weights"] for w in row)


def test_zigzag_temporal_trains_and_rejects_misuse(tmp_path, capsys):
    """--layout zigzag: sequence-supervised sharded training runs the
    balanced causal ring end-to-end from the CLI; misconfigurations
    (last supervision, window not divisible by 2x the seq axis) get
    direct messages instead of shard_map shape errors."""

    ckpt = str(tmp_path / "zck")
    assert main(["train", "--model", "temporal", "--sharded",
                 "--supervision", "sequence", "--layout", "zigzag",
                 "--steps", "2", "--ckpt", ckpt, "--groups", "4",
                 "--endpoints", "4", "--hidden", "16",
                 "--window", "16"]) == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["model"] == "temporal" and out["step"] == 2
    with pytest.raises(SystemExit, match="supervision sequence"):
        main(["train", "--model", "temporal", "--sharded",
              "--layout", "zigzag", "--steps", "1", "--groups", "4",
              "--endpoints", "4", "--hidden", "16", "--window", "16"])
    # window=6 divides the seq axis (2) but not 2x it — only the
    # zigzag check can catch this
    with pytest.raises(SystemExit, match="divisible by"):
        main(["train", "--model", "temporal", "--sharded",
              "--supervision", "sequence", "--layout", "zigzag",
              "--steps", "1", "--groups", "4", "--endpoints", "4",
              "--hidden", "16", "--window", "6"])


def test_sharded_rejects_indivisible_shapes(capsys):

    with pytest.raises(SystemExit):
        main(["train", "--model", "temporal", "--sharded", "--steps",
              "1", "--groups", "3", "--endpoints", "4", "--hidden",
              "16", "--window", "7"])


def test_sharded_mlp_trains(capsys):
    assert main(["train", "--sharded", "--steps", "2", "--groups", "8",
                 "--endpoints", "8", "--hidden", "16"]) == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["step"] == 2 and out["loss"] is not None


def test_help_lists_compute_subcommands(capsys):

    with pytest.raises(SystemExit):
        main(["--help"])
    help_text = capsys.readouterr().out
    assert "train" in help_text and "plan" in help_text


def test_moe_model_trains_and_plans(tmp_path, capsys):
    ckpt = str(tmp_path / "mck")
    assert main(["train", "--model", "moe", "--steps", "2",
                 "--ckpt", ckpt, "--groups", "8", "--endpoints", "6",
                 "--hidden", "16", "--experts", "2"]) == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["model"] == "moe" and out["step"] == 2
    assert main(["plan", "--model", "moe", "--ckpt", ckpt,
                 "--groups", "8", "--endpoints", "6", "--hidden", "16",
                 "--experts", "2"]) == 0
    plan = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert len(plan["weights"]) == 8
    assert all(0 <= w <= 255 for row in plan["weights"] for w in row)


def test_sharded_moe_trains_and_plans(tmp_path, capsys):
    """--sharded --model moe builds a data x expert mesh over the 8
    virtual CPU devices and trains through the all_to_all dispatch."""
    ckpt = str(tmp_path / "smck")
    assert main(["train", "--model", "moe", "--sharded", "--steps", "2",
                 "--ckpt", ckpt, "--groups", "16", "--endpoints", "4",
                 "--hidden", "16", "--experts", "4"]) == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["model"] == "moe" and out["step"] == 2
    assert main(["plan", "--model", "moe", "--sharded", "--ckpt", ckpt,
                 "--groups", "16", "--endpoints", "4", "--hidden", "16",
                 "--experts", "4"]) == 0
    plan = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert len(plan["weights"]) == 16


def test_sharded_moe_rejects_bad_expert_count(capsys):

    with pytest.raises(SystemExit):
        main(["train", "--model", "moe", "--sharded", "--steps", "1",
              "--groups", "16", "--endpoints", "4", "--hidden", "16",
              "--experts", "3"])


def test_deep_model_trains_and_plans(tmp_path, capsys):
    ckpt = str(tmp_path / "dck")
    assert main(["train", "--model", "deep", "--steps", "2",
                 "--ckpt", ckpt, "--groups", "8", "--endpoints", "6",
                 "--hidden", "16", "--stages", "3"]) == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["model"] == "deep" and out["step"] == 2
    assert main(["plan", "--model", "deep", "--ckpt", ckpt,
                 "--groups", "8", "--endpoints", "6", "--hidden", "16",
                 "--stages", "3"]) == 0
    plan = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert len(plan["weights"]) == 8


def test_sharded_deep_trains_and_plans(tmp_path, capsys):
    """--sharded --model deep runs the GPipe schedule over the 8
    virtual CPU devices (one stage per device)."""
    ckpt = str(tmp_path / "sdck")
    assert main(["train", "--model", "deep", "--sharded", "--steps", "2",
                 "--ckpt", ckpt, "--groups", "8", "--endpoints", "4",
                 "--hidden", "16", "--stages", "8",
                 "--microbatches", "2"]) == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["model"] == "deep" and out["step"] == 2
    assert main(["plan", "--model", "deep", "--sharded", "--ckpt", ckpt,
                 "--groups", "8", "--endpoints", "4", "--hidden", "16",
                 "--stages", "8", "--microbatches", "2"]) == 0
    plan = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert len(plan["weights"]) == 8


def test_sharded_deep_rejects_bad_stage_count(capsys):

    with pytest.raises(SystemExit):
        main(["train", "--model", "deep", "--sharded", "--steps", "1",
              "--groups", "8", "--endpoints", "4", "--hidden", "16",
              "--stages", "3"])


def test_sharded_deep_rejects_nonpositive_stages(capsys):

    with pytest.raises(SystemExit):
        main(["train", "--model", "deep", "--sharded", "--steps", "1",
              "--groups", "8", "--endpoints", "4", "--hidden", "16",
              "--stages", "0"])


def test_sharded_deep_dp_pp_composition(capsys):
    """--stages 4 on 8 devices: the spare factor becomes a data axis
    (dp x pp) instead of being rejected."""
    assert main(["train", "--model", "deep", "--sharded", "--steps",
                 "2", "--groups", "8", "--endpoints", "4", "--hidden",
                 "16", "--stages", "4", "--microbatches", "2"]) == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["step"] == 2 and out["loss"] is not None


def test_train_with_native_loader(capsys):
    """--loader native feeds training from the C++ pipeline (degrades
    to synthetic when no toolchain, so this passes either way)."""
    assert main(["train", "--loader", "native", "--steps", "3",
                 "--groups", "8", "--endpoints", "6",
                 "--hidden", "16"]) == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["step"] == 3 and out["loss"] is not None


def test_train_temporal_with_native_loader(capsys):
    """The temporal family streams windows from the C++ pipeline
    (window-mode loader; degrades to synthetic without a toolchain)."""
    assert main(["train", "--model", "temporal", "--loader", "native",
                 "--steps", "2", "--groups", "4", "--endpoints", "4",
                 "--hidden", "16", "--window", "6"]) == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["step"] == 2 and out["loss"] is not None


def test_train_temporal_sharded_with_native_loader(capsys):
    """All three long-context pieces compose from the CLI: the C++
    window pipeline feeds the data x seq ring-attention planner."""
    assert main(["train", "--model", "temporal", "--sharded",
                 "--loader", "native", "--steps", "2", "--groups", "8",
                 "--endpoints", "4", "--hidden", "16", "--window",
                 "8"]) == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["step"] == 2 and out["loss"] is not None


def test_native_loader_rejected_for_custom_batch_families(capsys):

    with pytest.raises(SystemExit):
        main(["train", "--model", "moe", "--loader", "native",
              "--steps", "1", "--groups", "8", "--endpoints", "4",
              "--hidden", "16"])


def test_train_profile_writes_trace(tmp_path, capsys):
    prof = str(tmp_path / "prof")
    assert main(["train", "--steps", "2", "--groups", "4",
                 "--endpoints", "4", "--hidden", "16",
                 "--profile", prof]) == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["step"] == 2
    import os
    found = [os.path.join(r, f) for r, _, fs in os.walk(prof) for f in fs]
    assert found, "profiler trace directory is empty"


def test_sharded_deep_remat_trains(capsys):
    assert main(["train", "--model", "deep", "--sharded", "--remat",
                 "--steps", "2", "--groups", "8", "--endpoints", "4",
                 "--hidden", "16", "--stages", "8",
                 "--microbatches", "2"]) == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["model"] == "deep" and out["step"] == 2


def test_guard_restores_after_transient_nan(tmp_path, capsys, monkeypatch):
    """--guard rolls back to the last checkpoint on a non-finite loss
    and continues with the next batch."""
    import math

    from aws_global_accelerator_controller_tpu.cmd import compute

    real_build = compute._build_model
    poisoned = {"fired": False}

    def build(args):
        model, run_step, run_plan_fwd = real_build(args)

        def guarded_step(params, opt_state, key):
            params, opt_state, loss = run_step(params, opt_state, key)
            if not poisoned["fired"]:
                poisoned["fired"] = True
                return params, opt_state, loss * float("nan")
            return params, opt_state, loss
        return model, guarded_step, run_plan_fwd

    monkeypatch.setattr(compute, "_build_model", build)
    ckpt = str(tmp_path / "gck")
    assert main(["train", "--guard", "--steps", "4", "--ckpt", ckpt,
                 "--save-every", "1", "--groups", "4",
                 "--endpoints", "4", "--hidden", "16"]) == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    # 4 batches, 1 discarded by the guard -> 3 APPLIED updates; the
    # step label must not count the rolled-back batch
    assert out["step"] == 3
    assert math.isfinite(out["loss"])
    assert poisoned["fired"]


def test_guard_aborts_after_persistent_divergence(capsys, monkeypatch):

    from aws_global_accelerator_controller_tpu.cmd import compute

    real_build = compute._build_model

    def build(args):
        model, run_step, run_plan_fwd = real_build(args)

        def always_nan(params, opt_state, key):
            params, opt_state, loss = run_step(params, opt_state, key)
            return params, opt_state, loss * float("nan")
        return model, always_nan, run_plan_fwd

    monkeypatch.setattr(compute, "_build_model", build)
    with pytest.raises(SystemExit, match="diverged"):
        main(["train", "--guard", "--steps", "20", "--groups", "4",
              "--endpoints", "4", "--hidden", "16"])


def test_sigterm_checkpoints_and_exits_cleanly(tmp_path):
    """Preemption safety: SIGTERM mid-training saves a final
    checkpoint at the exact applied-update step, reports
    preempted:true with exit 0, and a rerun resumes from that step —
    the k8s-eviction / TPU-pod-maintenance contract."""
    import os
    import signal
    import subprocess
    import sys
    import time

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    ckpt = tmp_path / "ck"
    cmd = [sys.executable, "-m",
           "aws_global_accelerator_controller_tpu", "train",
           "--model", "mlp", "--steps", "100000", "--groups", "16",
           "--endpoints", "4", "--hidden", "16",
           "--ckpt", str(ckpt), "--save-every", "50"]
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, text=True,
                            env=env, cwd=repo)
    try:
        # observable readiness instead of a fixed sleep: the first
        # periodic save proves the loop is past compile, the handler
        # is installed, and >= 50 steps applied -- robust on slow CI
        deadline = time.time() + 300
        while time.time() < deadline:
            if proc.poll() is not None:
                break
            if ckpt.exists() and any(ckpt.iterdir()):
                break
            time.sleep(0.25)
        assert ckpt.exists() and any(ckpt.iterdir()), \
            "no checkpoint appeared within 300s"
        proc.send_signal(signal.SIGTERM)
        out, err = proc.communicate(timeout=120)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.communicate()
    assert proc.returncode == 0, err[-2000:]
    line = json.loads(out.strip().splitlines()[-1])
    assert line["preempted"] is True
    assert line["step"] > 0, "no step completed before the signal"

    # resume: the checkpoint holds exactly the reported step
    proc2 = subprocess.run(
        [sys.executable, "-m", "aws_global_accelerator_controller_tpu",
         "train", "--model", "mlp", "--steps", "1", "--groups", "16",
         "--endpoints", "4", "--hidden", "16", "--ckpt", str(ckpt)],
        capture_output=True, text=True, timeout=300, env=env, cwd=repo)
    assert proc2.returncode == 0, proc2.stderr[-2000:]
    line2 = json.loads(proc2.stdout.strip().splitlines()[-1])
    assert line2["step"] == line["step"] + 1


def test_scoped_stop_signal_sets_event_and_restores_handlers():
    """The train CLI's signal scope must translate SIGTERM into the
    stop event AND put the host's handlers back on exit — an
    in-process caller (pytest, an embedding app) keeps its own
    KeyboardInterrupt behavior after training returns."""
    import os
    import signal as signal_mod
    import time

    from aws_global_accelerator_controller_tpu.signals import (
        ScopedStopSignal,
    )

    before_int = signal_mod.getsignal(signal_mod.SIGINT)
    before_term = signal_mod.getsignal(signal_mod.SIGTERM)
    with ScopedStopSignal() as stop:
        assert not stop.is_set()
        assert signal_mod.getsignal(signal_mod.SIGTERM) \
            is not before_term
        os.kill(os.getpid(), signal_mod.SIGTERM)
        for _ in range(200):
            if stop.is_set():
                break
            time.sleep(0.01)
        assert stop.is_set()
    assert signal_mod.getsignal(signal_mod.SIGINT) is before_int
    assert signal_mod.getsignal(signal_mod.SIGTERM) is before_term


def test_eval_reports_plan_quality(tmp_path, capsys):
    """eval: a trained checkpoint beats the uniform-plan baseline on
    held-out fleets; the fresh init does not — the go/no-go an
    operator runs before pointing --policy-checkpoint at it."""
    ckpt = str(tmp_path / "ck")
    assert main(["train", "--steps", "200", "--ckpt", ckpt,
                 "--groups", "32", "--endpoints", "8",
                 "--hidden", "32"]) == 0
    capsys.readouterr()
    assert main(["eval", "--ckpt", ckpt, "--groups", "32",
                 "--endpoints", "8", "--hidden", "32",
                 "--batches", "8"]) == 0
    trained = json.loads(capsys.readouterr().out.strip()
                         .splitlines()[-1])
    assert trained["step"] == 200
    assert trained["beats_uniform"] is True
    assert trained["plan_l1"] < trained["uniform_l1"]

    assert main(["eval", "--groups", "32", "--endpoints", "8",
                 "--hidden", "32", "--batches", "8"]) == 0
    fresh = json.loads(capsys.readouterr().out.strip()
                       .splitlines()[-1])
    assert fresh["step"] == 0
    assert fresh["plan_l1"] > trained["plan_l1"]


def test_eval_covers_other_families(capsys):
    for extra in (["--model", "temporal", "--window", "8"],
                  ["--model", "moe", "--experts", "2"],
                  ["--model", "deep", "--stages", "2"]):
        assert main(["eval", *extra, "--groups", "8",
                     "--endpoints", "4", "--hidden", "16",
                     "--batches", "2"]) == 0
        out = json.loads(capsys.readouterr().out.strip()
                         .splitlines()[-1])
        assert out["batches"] == 2
        import math
        assert math.isfinite(out["mean_loss"])


def test_train_eval_every_logs_heldout_loss(tmp_path, capsys, caplog):
    import logging

    with caplog.at_level(
            logging.INFO,
            logger="aws_global_accelerator_controller_tpu.cmd.compute"):
        assert main(["train", "--steps", "4", "--groups", "8",
                     "--endpoints", "4", "--hidden", "16",
                     "--eval-every", "2"]) == 0
    capsys.readouterr()
    evals = [r for r in caplog.records if "eval_loss" in r.getMessage()]
    assert len(evals) == 2  # steps 2 and 4


def test_preempt_exit_code_flag(tmp_path):
    """--preempt-exit: a SIGTERM-interrupted run exits with the
    configured code (the k8s Job restart contract) while still
    checkpointing; default stays 0 (tested above)."""
    import os
    import signal
    import subprocess
    import sys
    import time

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    ckpt = tmp_path / "ck"
    proc = subprocess.Popen(
        [sys.executable, "-m", "aws_global_accelerator_controller_tpu",
         "train", "--model", "mlp", "--steps", "100000",
         "--groups", "16", "--endpoints", "4", "--hidden", "16",
         "--ckpt", str(ckpt), "--save-every", "50",
         "--preempt-exit", "75"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=dict(os.environ, JAX_PLATFORMS="cpu"), cwd=repo)
    try:
        deadline = time.time() + 300
        while time.time() < deadline:
            if proc.poll() is not None:
                break
            if ckpt.exists() and any(ckpt.iterdir()):
                break
            time.sleep(0.25)
        proc.send_signal(signal.SIGTERM)
        out, err = proc.communicate(timeout=120)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.communicate()
    assert proc.returncode == 75, (proc.returncode, err[-1000:])
    line = json.loads(out.strip().splitlines()[-1])
    assert line["preempted"] is True and line["step"] > 0


def test_eval_bad_ckpt_is_a_clean_cli_error(tmp_path, capsys):

    with pytest.raises(SystemExit, match="no checkpoint found"):
        main(["eval", "--ckpt", str(tmp_path / "polcy"),
              "--groups", "8", "--endpoints", "4", "--hidden", "16"])


def test_temporal_train_knobs_chunk_and_flat_adam(capsys):
    """The staged single-chip levers are drivable from the CLI: a
    chunked-attention + flat-adam temporal run trains to a finite
    loss (chunk > S degenerates to one call; kernel path itself is
    pinned by tests/test_temporal_model.py)."""
    assert main(["train", "--model", "temporal", "--steps", "2",
                 "--groups", "2", "--endpoints", "4", "--window",
                 "16", "--hidden", "16", "--supervision", "sequence",
                 "--attention-chunk", "4", "--optimizer",
                 "flat_adam"]) == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["model"] == "temporal" and out["step"] == 2
    assert out["loss"] is not None


def test_sharded_rejects_flat_adam():
    """The raveled optimizer state has no axes for the planner's
    NamedShardings — the CLI must reject the pair loudly, not shard
    garbage."""
    with pytest.raises(SystemExit) as exc:
        main(["train", "--model", "temporal", "--sharded",
              "--steps", "1", "--groups", "4", "--endpoints", "4",
              "--window", "16", "--hidden", "16",
              "--optimizer", "flat_adam"])
    assert "flat_adam" in str(exc.value)


def test_attention_chunk_cli_validation():
    with pytest.raises(SystemExit) as exc:
        main(["train", "--model", "temporal", "--steps", "1",
              "--groups", "2", "--endpoints", "4", "--window", "16",
              "--hidden", "16", "--attention-chunk", "-4"])
    assert "attention-chunk" in str(exc.value)
    with pytest.raises(SystemExit) as exc:
        main(["train", "--model", "temporal", "--sharded",
              "--steps", "1", "--groups", "4", "--endpoints", "4",
              "--window", "16", "--hidden", "16",
              "--attention-chunk", "32"])
    assert "ring" in str(exc.value)


def test_attention_chunk_compile_failure_is_named(monkeypatch):
    """An on-chip Mosaic rejection of the chunked program (the
    --attention-chunk 32 path sits on the fused backward's head-gate
    edge) must surface as a named CLI error, not a raw compiler
    traceback (r4 ADVICE #2).  Other failures — and later-step
    failures — stay raw."""
    from aws_global_accelerator_controller_tpu.cmd import compute

    real_build = compute._build_model

    def build(args):
        model, run_step, run_plan_fwd = real_build(args)

        def broken_step(params, opt_state, key):
            raise ValueError("Mosaic failed: scoped vmem exceeded")
        return model, broken_step, run_plan_fwd

    monkeypatch.setattr(compute, "_build_model", build)
    argv = ["train", "--model", "temporal", "--steps", "2",
            "--groups", "2", "--endpoints", "4", "--window", "16",
            "--hidden", "16", "--supervision", "sequence"]
    with pytest.raises(SystemExit) as exc:
        main(argv + ["--attention-chunk", "32"])
    msg = str(exc.value)
    assert "--attention-chunk 32" in msg and "two-sweep" in msg
    assert "scoped vmem" in msg          # original cause preserved
    # without the knob the same failure propagates raw
    with pytest.raises(ValueError):
        main(argv)


def test_attention_chunk_unrelated_failure_stays_raw(monkeypatch):
    """A first-step failure WITHOUT a compiler signature must not be
    misattributed to --attention-chunk (review finding: an HBM OOM or
    optimizer error would otherwise point the user at the wrong
    knob)."""
    from aws_global_accelerator_controller_tpu.cmd import compute

    real_build = compute._build_model

    def build(args):
        model, run_step, run_plan_fwd = real_build(args)

        def broken_step(params, opt_state, key):
            raise ValueError("optimizer state mismatch")
        return model, broken_step, run_plan_fwd

    monkeypatch.setattr(compute, "_build_model", build)
    with pytest.raises(ValueError, match="optimizer state mismatch"):
        main(["train", "--model", "temporal", "--steps", "2",
              "--groups", "2", "--endpoints", "4", "--window", "16",
              "--hidden", "16", "--supervision", "sequence",
              "--attention-chunk", "32"])


def test_attention_chunk_hbm_oom_stays_raw(monkeypatch):
    """A plain HBM RESOURCE_EXHAUSTED (model too big for the chip, no
    Mosaic/Pallas involvement) must NOT be misattributed to
    --attention-chunk: the signature gate matches compiler-specific
    markers only (r5 ADVICE low)."""
    from aws_global_accelerator_controller_tpu.cmd import compute

    real_build = compute._build_model

    def build(args):
        model, run_step, run_plan_fwd = real_build(args)

        def broken_step(params, opt_state, key):
            raise ValueError(
                "RESOURCE_EXHAUSTED: Out of memory while trying to "
                "allocate 17179869184 bytes in HBM")
        return model, broken_step, run_plan_fwd

    monkeypatch.setattr(compute, "_build_model", build)
    with pytest.raises(ValueError, match="RESOURCE_EXHAUSTED"):
        main(["train", "--model", "temporal", "--steps", "2",
              "--groups", "2", "--endpoints", "4", "--window", "16",
              "--hidden", "16", "--supervision", "sequence",
              "--attention-chunk", "32"])


def test_attention_chunk_rejected_for_non_temporal_families():
    with pytest.raises(SystemExit) as exc:
        main(["train", "--model", "mlp", "--steps", "1",
              "--groups", "4", "--endpoints", "4", "--hidden", "16",
              "--attention-chunk", "8"])
    assert "temporal" in str(exc.value)


def test_flat_adam_works_across_families(capsys):
    """The optimizer knob is family-agnostic single-chip: every family
    trains a step with the raveled update."""
    for model in ("mlp", "deep", "moe"):
        assert main(["train", "--model", model, "--steps", "1",
                     "--groups", "4", "--endpoints", "4", "--hidden",
                     "16", "--optimizer", "flat_adam"]) == 0
        out = json.loads(
            capsys.readouterr().out.strip().splitlines()[-1])
        assert out["model"] == model and out["loss"] is not None


def test_flat_adam_checkpoint_restores_in_eval_and_plan(tmp_path,
                                                        capsys):
    """eval/plan are params-only consumers: a checkpoint trained with
    --optimizer flat_adam (FlatAdamState, not optax's per-leaf tree)
    must restore cleanly there (restore_params is optimizer-structure
    agnostic)."""
    ckpt = str(tmp_path / "flatck")
    assert main(["train", "--steps", "2", "--ckpt", ckpt,
                 "--groups", "4", "--endpoints", "4", "--hidden",
                 "16", "--save-every", "2", "--optimizer",
                 "flat_adam"]) == 0
    capsys.readouterr()
    assert main(["eval", "--ckpt", ckpt, "--groups", "4",
                 "--endpoints", "4", "--hidden", "16",
                 "--batches", "2"]) == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["mean_loss"] is not None
    assert main(["plan", "--ckpt", ckpt, "--groups", "4",
                 "--endpoints", "4", "--hidden", "16"]) == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["weights"]


def test_resume_with_different_optimizer_is_a_clean_cli_error(
        tmp_path, capsys):
    """Resuming an adam checkpoint with --optimizer flat_adam (or vice
    versa) has mismatched opt_state tree structures — that must be a
    named CLI error with the fix, not a raw orbax traceback."""
    ckpt = str(tmp_path / "adamck")
    assert main(["train", "--steps", "1", "--ckpt", ckpt,
                 "--groups", "4", "--endpoints", "4", "--hidden",
                 "16", "--save-every", "1"]) == 0
    capsys.readouterr()
    with pytest.raises(SystemExit) as exc:
        main(["train", "--steps", "1", "--ckpt", ckpt,
              "--groups", "4", "--endpoints", "4", "--hidden", "16",
              "--optimizer", "flat_adam"])
    assert "--optimizer" in str(exc.value)


def test_plan_bad_ckpt_is_a_clean_cli_error(tmp_path):
    with pytest.raises(SystemExit) as exc:
        main(["plan", "--ckpt", str(tmp_path / "nope"),
              "--groups", "4", "--endpoints", "4", "--hidden", "16"])
    assert "no checkpoint" in str(exc.value)
    assert not (tmp_path / "nope").exists()  # no orbax littering
