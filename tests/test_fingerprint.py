"""Steady-state fast-path unit tests: the fingerprint cache's
origin/record/invalidate lifecycle and the reconcile dispatch's
skip/sweep behavior (reconcile/fingerprint.py + reconcile/__init__.py).
"""
import zlib

import pytest

from aws_global_accelerator_controller_tpu import metrics
from aws_global_accelerator_controller_tpu.kube.workqueue import (
    ItemExponentialFailureRateLimiter,
    RateLimitingQueue,
)
from aws_global_accelerator_controller_tpu.reconcile import (
    Result,
    process_next_work_item,
)
from aws_global_accelerator_controller_tpu.reconcile.fingerprint import (
    ORIGIN_EVENT,
    ORIGIN_RESYNC,
    ORIGIN_SWEEP,
    FingerprintCache,
    FingerprintConfig,
    in_sweep,
    invalidate_all_caches,
    note_provider_mutation,
)


class FakeMeta:
    def __init__(self, generation=1):
        self.generation = generation


class FakeObj:
    def __init__(self, key, value="v", generation=1):
        self.k = key
        self.value = value
        self.metadata = FakeMeta(generation)

    def key(self):
        return self.k

    def deep_copy(self):
        return FakeObj(self.k, self.value, self.metadata.generation)


def fp_fn(obj):
    return (obj.k, obj.value)


def make_cache(**kw):
    return FingerprintCache("test-queue", fp_fn,
                            FingerprintConfig(**kw))


def make_queue():
    return RateLimitingQueue(
        rate_limiter=ItemExponentialFailureRateLimiter(0.001, 0.05))


def run_one(queue, obj_by_key, cache, upsert=None, delete=None):
    return process_next_work_item(
        queue, lambda k: obj_by_key[k],
        delete or (lambda key: Result()),
        upsert or (lambda obj: Result()),
        get_timeout=1.0, fingerprints=cache)


def sweep_wave_for(key, every):
    """The wave on which ``key`` is due for its deep verify."""
    return zlib.crc32(key.encode()) % every


# ---------------------------------------------------------------------------
# cache lifecycle
# ---------------------------------------------------------------------------

def test_record_then_match_requires_same_generation_and_fields():
    cache = make_cache()
    obj = FakeObj("ns/a", "v1", generation=3)
    cache.record("ns/a", obj)
    assert cache.matches("ns/a", obj)
    assert not cache.matches("ns/a", FakeObj("ns/a", "v2", generation=3))
    assert not cache.matches("ns/a", FakeObj("ns/a", "v1", generation=4))


def test_event_invalidates_and_claims_origin():
    cache = make_cache()
    obj = FakeObj("ns/a")
    cache.record("ns/a", obj)
    cache.note_event("ns/a")
    assert not cache.matches("ns/a", obj), \
        "a real watch event must drop the record"
    assert cache.claim_origin("ns/a") == ORIGIN_EVENT
    assert cache.claim_origin("ns/a") is None, "claim consumes"


def test_event_origin_not_demoted_by_resync():
    cache = make_cache(sweep_every=1000)
    cache.note_event("ns/a")
    assert cache.note_resync("ns/a", wave=0) == ORIGIN_EVENT
    assert cache.claim_origin("ns/a") == ORIGIN_EVENT


def test_sweep_tier_key_stable_and_spread():
    every = 10
    cache = make_cache(sweep_every=every)
    keys = [f"ns/svc{i:03d}" for i in range(200)]
    # each key is due exactly on its own wave, every ``every`` waves
    for key in keys:
        due_wave = sweep_wave_for(key, every)
        assert cache.note_resync(key, due_wave) == ORIGIN_SWEEP
        cache.claim_origin(key)
        assert cache.note_resync(key, due_wave + 1) == ORIGIN_RESYNC
        cache.claim_origin(key)
        assert cache.note_resync(key, due_wave + every) == ORIGIN_SWEEP
        cache.claim_origin(key)
    # the fleet's sweeps are spread: each wave carries roughly 1/every
    per_wave = [sum(1 for k in keys if sweep_wave_for(k, every) == w)
                for w in range(every)]
    assert all(p < len(keys) / 2 for p in per_wave), \
        f"sweep bunched: {per_wave}"
    assert sum(per_wave) == len(keys)


def test_disabled_config_never_matches_or_records():
    cache = make_cache(enabled=False)
    obj = FakeObj("ns/a")
    cache.record("ns/a", obj)
    assert len(cache) == 0
    assert not cache.matches("ns/a", obj)


def test_bounded_entries_evict_oldest():
    cache = make_cache(max_entries=3)
    for i in range(5):
        cache.record(f"ns/{i}", FakeObj(f"ns/{i}"))
    assert len(cache) == 3
    assert not cache.matches("ns/0", FakeObj("ns/0"))
    assert cache.matches("ns/4", FakeObj("ns/4"))


def test_invalidate_all_caches_global_hook():
    cache = make_cache()
    cache.record("ns/a", FakeObj("ns/a"))
    invalidate_all_caches("circuit_open:test")
    assert len(cache) == 0


def test_circuit_open_transition_drops_fingerprints():
    """The resilience-layer signal: a breaker transitioning to open
    invalidates every recorded fingerprint."""
    from aws_global_accelerator_controller_tpu.resilience.breaker import (
        CircuitBreaker,
    )

    cache = make_cache()
    cache.record("ns/a", FakeObj("ns/a"))
    breaker = CircuitBreaker(region="fp-test", window=10.0, min_calls=2,
                             failure_threshold=0.5, open_seconds=5.0)
    breaker.record_failure()
    breaker.record_failure()
    assert breaker.state() == "open"
    assert len(cache) == 0, \
        "circuit open must invalidate recorded fingerprints"


def test_sweep_context_attributes_mutations_to_drift_repair():
    cache = make_cache()
    reg = metrics.default_registry
    repairs = reg.counter_value("drift_repairs_total")
    verifies = reg.counter_value("drift_sweep_verifies_total")
    assert not in_sweep()
    note_provider_mutation()   # outside a sweep: not a repair
    assert reg.counter_value("drift_repairs_total") == repairs
    with cache.sweep_verify():
        assert in_sweep()
        note_provider_mutation()
    assert not in_sweep()
    assert reg.counter_value("drift_repairs_total") == repairs + 1
    assert reg.counter_value("drift_sweep_verifies_total") == verifies + 1


def test_sweep_every_zero_disables_the_sweep():
    """CLI convention: 0 disables — no delivery is ever sweep-tagged,
    so unchanged objects never reach the provider (and drift goes
    undetected, as documented)."""
    cache = make_cache(sweep_every=0)
    for wave in range(25):
        assert cache.note_resync("ns/a", wave) == ORIGIN_RESYNC
        cache.claim_origin("ns/a")


def test_uncoalesced_mutation_in_sweep_counts_as_repair():
    """Sweep repairs made through the NON-coalesced mutation surface
    (accelerator/listener lifecycle — e.g. re-enabling an accelerator
    disabled out-of-band) are attributed too: the resilient wrapper
    counts them on success when the calling thread is in a sweep."""
    from aws_global_accelerator_controller_tpu.cloudprovider.aws.fake import (  # noqa: E501
        FakeAWSCloud,
    )
    from aws_global_accelerator_controller_tpu.resilience import (
        ResilientAPIs,
    )
    from aws_global_accelerator_controller_tpu.resilience.wrapper import (
        FAKE_CLOUD_CONFIG,
    )

    cloud = FakeAWSCloud()
    apis = ResilientAPIs(cloud, region="fp-repair",
                         config=FAKE_CLOUD_CONFIG)
    acc = apis.ga.create_accelerator("a", "IPV4", True, {})
    reg = metrics.default_registry
    repairs = reg.counter_value("drift_repairs_total")

    # outside a sweep: a mutation is ordinary convergence work
    apis.ga.update_accelerator(acc.accelerator_arn, enabled=True)
    assert reg.counter_value("drift_repairs_total") == repairs

    cache = make_cache()
    with cache.sweep_verify():
        apis.ga.update_accelerator(acc.accelerator_arn, enabled=True)
        apis.ga.describe_accelerator(acc.accelerator_arn)  # read: free
    assert reg.counter_value("drift_repairs_total") == repairs + 1


def test_resync_enqueue_answers_unchanged_without_queue_churn():
    """The enqueue-time gate (controller.base.resync_enqueue): an
    unchanged object never touches the workqueue — so a parked or
    backing-off key is never converted into an immediate retry by the
    next resync wave — while changed keys ride add_rate_limited (the
    per-key failure backoff stays in force)."""
    from aws_global_accelerator_controller_tpu.controller.base import (
        resync_enqueue,
    )

    cache = make_cache(sweep_every=1000)
    q = make_queue()
    obj = FakeObj("ns/a")
    cache.record("ns/a", obj)
    reg = metrics.default_registry
    skips = reg.counter_value("reconcile_fastpath_skips_total",
                              {"controller": "test-queue"})
    wave = sweep_wave_for("ns/a", 1000) + 1

    resync_enqueue(cache, q, obj, wave)
    assert len(q) == 0, "unchanged object must not be enqueued"
    assert reg.counter_value("reconcile_fastpath_skips_total",
                             {"controller": "test-queue"}) == skips + 1
    assert cache.claim_origin("ns/a") is None, \
        "the pending origin must be consumed with the skip"

    # changed object (stale record): rate-limited path, failure
    # accounting armed
    resync_enqueue(cache, q, FakeObj("ns/a", "v2"), wave)
    item, _ = q.get(timeout=1.0)
    assert item == "ns/a"
    assert q.num_requeues("ns/a") == 1, \
        "the backstop enqueue must ride the rate limiter"

    # sweep-due wave: enqueued even though the record matches
    q2 = make_queue()
    cache2 = make_cache(sweep_every=7)
    cache2.record("ns/a", obj)
    resync_enqueue(cache2, q2, obj, sweep_wave_for("ns/a", 7))
    item, _ = q2.get(timeout=1.0)
    assert item == "ns/a", "sweep-due keys must reach the queue"


# ---------------------------------------------------------------------------
# reconcile dispatch
# ---------------------------------------------------------------------------

def test_resync_origin_with_matching_fingerprint_skips():
    cache = make_cache(sweep_every=1000)
    q = make_queue()
    obj = FakeObj("ns/a")
    objs = {"ns/a": obj}
    synced = []

    # first pass: event origin, full sync, fingerprint recorded
    cache.note_event("ns/a")
    q.add("ns/a")
    run_one(q, objs, cache, upsert=lambda o: synced.append(o) or Result())
    assert len(synced) == 1

    # resync re-delivery of the unchanged object: skipped before the
    # process func (no provider calls, no sync)
    reg = metrics.default_registry
    skips = reg.counter_value("reconcile_fastpath_skips_total",
                              {"controller": "test-queue"})
    origin = cache.note_resync("ns/a", wave=sweep_wave_for("ns/a", 1000) + 1)
    assert origin == ORIGIN_RESYNC
    q.add("ns/a")
    run_one(q, objs, cache, upsert=lambda o: synced.append(o) or Result())
    assert len(synced) == 1, "matching fingerprint must skip the sync"
    assert reg.counter_value("reconcile_fastpath_skips_total",
                             {"controller": "test-queue"}) == skips + 1
    assert len(q) == 0 and q.num_requeues("ns/a") == 0


def test_resync_origin_with_changed_object_syncs():
    cache = make_cache(sweep_every=1000)
    q = make_queue()
    objs = {"ns/a": FakeObj("ns/a", "v1")}
    synced = []
    cache.note_event("ns/a")
    q.add("ns/a")
    run_one(q, objs, cache, upsert=lambda o: synced.append(o) or Result())

    objs["ns/a"] = FakeObj("ns/a", "v2")   # drifted desired state
    cache.note_resync("ns/a", wave=sweep_wave_for("ns/a", 1000) + 1)
    q.add("ns/a")
    run_one(q, objs, cache, upsert=lambda o: synced.append(o) or Result())
    assert len(synced) == 2, "changed object must take the full sync"


def test_sweep_origin_bypasses_gate_and_marks_context():
    cache = make_cache(sweep_every=7)
    q = make_queue()
    obj = FakeObj("ns/a")
    objs = {"ns/a": obj}
    cache.record("ns/a", obj)   # warm fingerprint — would skip
    seen = []

    origin = cache.note_resync("ns/a", wave=sweep_wave_for("ns/a", 7))
    assert origin == ORIGIN_SWEEP
    q.add("ns/a")
    run_one(q, objs, cache,
            upsert=lambda o: seen.append(in_sweep()) or Result())
    assert seen == [True], \
        "sweep must run the full sync inside the sweep context"


def test_sweep_with_stale_fingerprint_is_a_plain_sync():
    """A sweep delivery of a changed (or never-synced) object is an
    ordinary sync: no sweep context, no deep-verify counting — its
    real convergence work must not masquerade as drift repair."""
    cache = make_cache(sweep_every=7)
    q = make_queue()
    objs = {"ns/a": FakeObj("ns/a", "changed")}
    reg = metrics.default_registry
    verifies = reg.counter_value("drift_sweep_verifies_total")
    seen = []

    origin = cache.note_resync("ns/a", wave=sweep_wave_for("ns/a", 7))
    assert origin == ORIGIN_SWEEP
    q.add("ns/a")
    run_one(q, objs, cache,
            upsert=lambda o: seen.append(in_sweep()) or Result())
    assert seen == [False], "stale fingerprint: plain sync, no context"
    assert reg.counter_value("drift_sweep_verifies_total") == verifies


def test_error_invalidates_fingerprint():
    cache = make_cache(sweep_every=1000)
    q = make_queue()
    obj = FakeObj("ns/a")
    objs = {"ns/a": obj}
    cache.note_event("ns/a")
    q.add("ns/a")
    run_one(q, objs, cache)          # success: recorded
    assert cache.matches("ns/a", obj)

    def boom(o):
        raise RuntimeError("provider brownout")

    cache.note_event("ns/a")
    q.add("ns/a")
    run_one(q, objs, cache, upsert=boom)
    assert not cache.matches("ns/a", obj), \
        "a failed sync must invalidate the record"


def test_unknown_origin_takes_full_path():
    """A key added without any origin note (direct add) must sync —
    the gate only answers resync-originated dispatches."""
    cache = make_cache()
    q = make_queue()
    obj = FakeObj("ns/a")
    objs = {"ns/a": obj}
    cache.record("ns/a", obj)       # warm record
    synced = []
    q.add("ns/a")
    run_one(q, objs, cache, upsert=lambda o: synced.append(o) or Result())
    assert len(synced) == 1


def test_delete_invalidates_record():
    from aws_global_accelerator_controller_tpu.errors import NotFoundError

    cache = make_cache()
    q = make_queue()
    obj = FakeObj("ns/a")
    cache.record("ns/a", obj)

    def gone(key):
        raise NotFoundError("Service", key)

    deleted = []
    q.add("ns/a")
    process_next_work_item(
        q, gone, lambda key: deleted.append(key) or Result(),
        lambda o: Result(), get_timeout=1.0, fingerprints=cache)
    assert deleted == ["ns/a"]
    assert not cache.matches("ns/a", obj)


@pytest.mark.parametrize("outcome", ["requeue", "requeue_after"])
def test_incomplete_sync_does_not_record(outcome):
    cache = make_cache()
    q = make_queue()
    obj = FakeObj("ns/a")
    objs = {"ns/a": obj}
    res = (Result(requeue=True) if outcome == "requeue"
           else Result(requeue_after=0.01))
    cache.note_event("ns/a")
    q.add("ns/a")
    run_one(q, objs, cache, upsert=lambda o: res)
    assert not cache.matches("ns/a", obj), \
        "an unconverged sync must not record a fingerprint"
