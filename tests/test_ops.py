"""TPU ops tests: weight planner (jax + pallas-interpret) and membership diff."""
import numpy as np
import jax
import jax.numpy as jnp

from aws_global_accelerator_controller_tpu.ops import (
    masked_softmax,
    membership_diff,
    plan_weights,
)
from aws_global_accelerator_controller_tpu.ops.diff import EMPTY, hash_ids
from aws_global_accelerator_controller_tpu.ops.pallas_weights import (
    plan_weights_pallas,
)


def test_masked_softmax_sums_to_one_over_valid():
    scores = jnp.array([[1.0, 2.0, 3.0, 4.0]])
    mask = jnp.array([[True, True, False, True]])
    p = masked_softmax(scores, mask)
    assert p[0, 2] == 0.0
    np.testing.assert_allclose(float(p.sum()), 1.0, rtol=1e-5)


def test_masked_softmax_all_masked_row_is_zero_not_nan():
    p = masked_softmax(jnp.ones((2, 3)), jnp.zeros((2, 3), bool))
    assert not np.any(np.isnan(np.asarray(p)))
    assert np.all(np.asarray(p) == 0.0)


def test_plan_weights_uniform():
    scores = jnp.zeros((1, 4))
    mask = jnp.ones((1, 4), bool)
    w = plan_weights(scores, mask)
    assert w.dtype == jnp.int32
    np.testing.assert_array_equal(np.asarray(w), [[64, 64, 64, 64]])


def test_plan_weights_respects_mask_and_bf16():
    scores = jnp.asarray([[10.0, 0.0, 0.0]], dtype=jnp.bfloat16)
    mask = jnp.array([[True, False, True]])
    w = np.asarray(plan_weights(scores, mask))
    assert w[0, 1] == 0
    assert w[0, 0] > w[0, 2]
    assert w.sum() in (254, 255, 256)  # rounding


def test_pallas_matches_reference():
    key = jax.random.PRNGKey(0)
    scores = jax.random.normal(key, (13, 37))  # deliberately unaligned
    mask = jax.random.bernoulli(jax.random.PRNGKey(1), 0.7, (13, 37))
    ref = np.asarray(plan_weights(scores, mask))
    pal = np.asarray(plan_weights_pallas(scores, mask))
    np.testing.assert_array_equal(ref, pal)


def test_membership_diff_matches_python_sets():
    rng = np.random.default_rng(0)
    G, E = 16, 24
    desired = np.full((G, E), int(EMPTY), dtype=np.int32)
    current = np.full((G, E), int(EMPTY), dtype=np.int32)
    for g in range(G):
        d = rng.choice(1000, size=rng.integers(0, E), replace=False)
        c = rng.choice(1000, size=rng.integers(0, E), replace=False)
        desired[g, :len(d)] = d
        current[g, :len(c)] = c
    to_add, to_remove = membership_diff(jnp.asarray(desired),
                                        jnp.asarray(current))
    to_add, to_remove = np.asarray(to_add), np.asarray(to_remove)
    for g in range(G):
        dset = set(desired[g][desired[g] != int(EMPTY)])
        cset = set(current[g][current[g] != int(EMPTY)])
        got_add = set(desired[g][to_add[g]])
        got_rem = set(current[g][to_remove[g]])
        assert got_add == dset - cset, f"group {g} add"
        assert got_rem == cset - dset, f"group {g} remove"


def test_hash_ids_stable_and_distinct():
    arns = [f"arn:aws:elasticloadbalancing:us-east-1:1:loadbalancer/net/l{i}/x"
            for i in range(100)]
    h1 = hash_ids(arns)
    h2 = hash_ids(arns)
    np.testing.assert_array_equal(np.asarray(h1), np.asarray(h2))
    assert len(set(np.asarray(h1).tolist())) == 100
    assert np.all(np.asarray(h1) >= 0)


def test_pallas_fused_mlp_matches_model():
    from aws_global_accelerator_controller_tpu.models.traffic import (
        TrafficPolicyModel,
        synthetic_batch,
    )
    from aws_global_accelerator_controller_tpu.ops.pallas_mlp import (
        forward_pallas,
    )

    model = TrafficPolicyModel(feature_dim=8, hidden_dim=64)
    params = model.init_params(jax.random.PRNGKey(0))
    batch = synthetic_batch(jax.random.PRNGKey(1), groups=5, endpoints=11,
                            feature_dim=8)
    # forward_dense explicitly: on TPU, plain forward (serve='auto')
    # dispatches to the fused kernel itself and the comparison would be
    # a tautology
    ref = np.asarray(model.forward_dense(params, batch.features,
                                         batch.mask))
    fused = np.asarray(forward_pallas(params, batch.features, batch.mask))
    # both paths run bf16 matmuls with f32 accumulation rounded to bf16,
    # so in interpret mode (conftest pins cpu) the integer weights are
    # bit-equal.  Compiled TPU (running this file unpinned) contracts
    # ±1 weight unit: XLA's epilogue fusion moves the f32->bf16
    # rounding points (pallas_mlp docstring).
    if jax.default_backend() == "tpu":
        np.testing.assert_allclose(ref, fused, atol=1)
    else:
        np.testing.assert_array_equal(ref, fused)
    assert np.all(fused[~np.asarray(batch.mask)] == 0)
    assert fused.dtype == np.int32


def test_model_serve_dispatch():
    """TrafficPolicyModel.serve wires the fused kernel into the
    user-facing forward: serve='fused' must equal the dense path
    bit-for-bit (the kernel test above proves the kernel itself; this
    proves the MODEL dispatches to it)."""
    import pytest

    from aws_global_accelerator_controller_tpu.models.traffic import (
        TrafficPolicyModel,
        synthetic_batch,
    )

    dense = TrafficPolicyModel(hidden_dim=32, serve="dense")
    fused = TrafficPolicyModel(hidden_dim=32, serve="fused")
    # serve='dense' pins the XLA path on every backend, so this stays a
    # real cross-implementation comparison on TPU too
    params = dense.init_params(jax.random.PRNGKey(0))
    batch = synthetic_batch(jax.random.PRNGKey(1), groups=12,
                            endpoints=10)
    want = np.asarray(dense.forward(params, batch.features, batch.mask))
    got = np.asarray(fused.forward(params, batch.features, batch.mask))
    np.testing.assert_array_equal(got, want)
    with pytest.raises(ValueError, match="serve"):
        TrafficPolicyModel(serve="gpu")
