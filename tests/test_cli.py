"""CLI tests (reference cmd/: root/controller/webhook/version commands)."""
import subprocess
import sys


def run_cli(*args, timeout=30):
    return subprocess.run(
        [sys.executable, "-m", "aws_global_accelerator_controller_tpu",
         *args],
        capture_output=True, text=True, timeout=timeout)


def test_version():
    res = run_cli("version")
    assert res.returncode == 0
    assert "Version" in res.stdout
    assert "Revision" in res.stdout
    assert "Build" in res.stdout


def test_help_lists_subcommands():
    res = run_cli("--help")
    assert res.returncode == 0
    for sub in ("controller", "webhook", "version"):
        assert sub in res.stdout


def test_webhook_requires_tls_files_with_ssl():
    res = run_cli("webhook", "--ssl")
    assert res.returncode == 2
    assert "tls-cert-file" in res.stderr


def test_no_subcommand_errors():
    res = run_cli()
    assert res.returncode != 0


def test_controller_demo_converges(tmp_path):
    """Drive the full binary: demo seed -> convergence in the logs, then
    SIGTERM for a clean shutdown.  Polls the log file for the convergence
    markers instead of sleeping a fixed interval."""
    import signal
    import time

    log_path = tmp_path / "demo.log"
    markers = ("Global Accelerator created", "Route53 record set is created")
    with open(log_path, "w") as log_file:
        proc = subprocess.Popen(
            [sys.executable, "-m", "aws_global_accelerator_controller_tpu",
             "controller", "--demo", "--health-port", "0"],
            stdout=log_file, stderr=subprocess.STDOUT, text=True,
            cwd="/root/repo")
        try:
            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline:
                out = log_path.read_text()
                if all(m in out for m in markers):
                    break
                time.sleep(0.2)
            proc.send_signal(signal.SIGTERM)
            proc.wait(timeout=15)
        finally:
            if proc.poll() is None:
                proc.kill()
    out = log_path.read_text()
    for m in markers:
        assert m in out
    assert "shutting down" in out
