"""CLI tests (reference cmd/: root/controller/webhook/version commands)."""
import subprocess
import sys


def run_cli(*args, timeout=30):
    return subprocess.run(
        [sys.executable, "-m", "aws_global_accelerator_controller_tpu",
         *args],
        capture_output=True, text=True, timeout=timeout)


def test_version():
    res = run_cli("version")
    assert res.returncode == 0
    assert "Version" in res.stdout
    assert "Revision" in res.stdout
    assert "Build" in res.stdout


def test_help_lists_subcommands():
    res = run_cli("--help")
    assert res.returncode == 0
    for sub in ("controller", "webhook", "version"):
        assert sub in res.stdout


def test_webhook_requires_tls_files_with_ssl():
    res = run_cli("webhook", "--ssl")
    assert res.returncode == 2
    assert "tls-cert-file" in res.stderr


def test_no_subcommand_errors():
    res = run_cli()
    assert res.returncode != 0


def test_controller_demo_converges(tmp_path):
    """Drive the full binary: demo seed -> convergence in the logs, then
    SIGTERM for a clean shutdown.  Polls the log file for the convergence
    markers instead of sleeping a fixed interval."""
    import signal
    import time

    log_path = tmp_path / "demo.log"
    markers = ("Global Accelerator created", "Route53 record set is created")
    with open(log_path, "w") as log_file:
        proc = subprocess.Popen(
            [sys.executable, "-m", "aws_global_accelerator_controller_tpu",
             "controller", "--demo", "--health-port", "0"],
            stdout=log_file, stderr=subprocess.STDOUT, text=True,
            cwd="/root/repo")
        try:
            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline:
                out = log_path.read_text()
                if all(m in out for m in markers):
                    break
                time.sleep(0.2)
            proc.send_signal(signal.SIGTERM)
            proc.wait(timeout=15)
        finally:
            if proc.poll() is None:
                proc.kill()
    out = log_path.read_text()
    for m in markers:
        assert m in out
    assert "shutting down" in out


def test_controller_shard_flags_validated():
    """--shards / --shard-id (ISSUE 8): bad values abort before any
    backend is built."""
    res = run_cli("controller", "--shards", "0")
    assert res.returncode != 0
    assert "--shards" in (res.stderr + res.stdout)
    res = run_cli("controller", "--shards", "4", "--shard-id", "7")
    assert res.returncode != 0
    assert "out of range" in (res.stderr + res.stdout)
    res = run_cli("controller", "--shards", "4", "--shard-id", "x")
    assert res.returncode != 0
    assert "integer or 'auto'" in (res.stderr + res.stdout)


def test_controller_autotune_flags_validated():
    """--autotune-pin / --autotune-interval (ISSUE 15): a typo'd knob
    name or malformed pin aborts before any backend is built."""
    res = run_cli("controller", "--autotune-pin", "no.such.knob=1")
    assert res.returncode != 0
    assert "unknown knob" in (res.stderr + res.stdout)
    res = run_cli("controller", "--autotune-pin", "coalescer.linger")
    assert res.returncode != 0
    assert "KNOB=VALUE" in (res.stderr + res.stdout)
    res = run_cli("controller", "--autotune-pin",
                  "coalescer.linger=abc")
    assert res.returncode != 0
    assert "not a number" in (res.stderr + res.stdout)
    res = run_cli("controller", "--autotune-interval", "0")
    assert res.returncode != 0
    assert "--autotune-interval" in (res.stderr + res.stdout)


def test_controller_demo_converges_sharded(tmp_path):
    """The demo fleet converges under --shards 4 --shard-id auto: the
    sharded path (shard-lease manager + per-shard cohorts) drives the
    real binary end to end, one replica owning every shard."""
    import signal
    import time

    log = tmp_path / "demo-sharded.log"
    with open(log, "w") as out:
        proc = subprocess.Popen(
            [sys.executable, "-m",
             "aws_global_accelerator_controller_tpu",
             "controller", "--demo", "--smoke", "60",
             "--shards", "4", "--health-port", "0"],
            stdout=out, stderr=subprocess.STDOUT)
        try:
            deadline = time.monotonic() + 90
            while proc.poll() is None and time.monotonic() < deadline:
                time.sleep(0.25)
            if proc.poll() is None:
                proc.send_signal(signal.SIGTERM)
                proc.wait(timeout=15)
        finally:
            if proc.poll() is None:
                proc.kill()
    assert proc.returncode == 0, log.read_text()[-2000:]
    text = log.read_text()
    assert "smoke: demo fleet converged" in text
    assert "shard lease manager" in text


def test_controller_regions_requires_fake_cloud():
    """--regions (ISSUE 14) aborts without a fake backend: the
    simulated region gateway is what backs the topology layer."""
    res = run_cli("controller", "--real", "--regions",
                  "us-west-2,eu-west-1")
    assert res.returncode != 0
    assert "--regions requires the fake cloud" in (res.stderr
                                                   + res.stdout)


def test_controller_demo_converges_multi_region(tmp_path):
    """The demo fleet converges with the multi-region topology armed
    (--regions): the per-region aggregator and digest gate ride the
    real binary end to end."""
    import signal
    import time

    log = tmp_path / "demo-regions.log"
    with open(log, "w") as out:
        proc = subprocess.Popen(
            [sys.executable, "-m",
             "aws_global_accelerator_controller_tpu",
             "controller", "--demo", "--smoke", "60",
             "--regions", "us-west-2,eu-west-1,ap-northeast-1",
             "--health-port", "0"],
            stdout=out, stderr=subprocess.STDOUT)
        try:
            deadline = time.monotonic() + 90
            while proc.poll() is None and time.monotonic() < deadline:
                time.sleep(0.25)
            if proc.poll() is None:
                proc.send_signal(signal.SIGTERM)
                proc.wait(timeout=15)
        finally:
            if proc.poll() is None:
                proc.kill()
    assert proc.returncode == 0, log.read_text()[-2000:]
    assert "smoke: demo fleet converged" in log.read_text()
