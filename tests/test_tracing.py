"""Tracing subsystem: span nesting, ring buffer, reconcile-path spans,
and the /traces endpoint."""
import json
import sys
import urllib.request

import pytest

from aws_global_accelerator_controller_tpu.metrics import HealthServer
from aws_global_accelerator_controller_tpu.tracing import (
    Tracer,
    default_tracer,
    traced,
)

sys.path.insert(0, "tests")
from harness import Cluster, wait_until  # noqa: E402

from aws_global_accelerator_controller_tpu.apis import (  # noqa: E402
    AWS_GLOBAL_ACCELERATOR_MANAGED_ANNOTATION,
    AWS_LOAD_BALANCER_TYPE_ANNOTATION,
)
from aws_global_accelerator_controller_tpu.kube.objects import (  # noqa: E402
    LoadBalancerIngress,
    LoadBalancerStatus,
    ObjectMeta,
    Service,
    ServicePort,
    ServiceSpec,
    ServiceStatus,
)


def test_span_nesting_and_trace_ids():
    tr = Tracer()
    with tr.span("outer", queue="q") as outer:
        with tr.span("inner") as inner:
            assert tr.current() is inner
        assert tr.current() is outer
    spans = tr.recent()
    assert [s["name"] for s in spans] == ["inner", "outer"]
    inner_d, outer_d = spans
    assert inner_d["parent_id"] == outer_d["span_id"]
    assert inner_d["trace_id"] == outer_d["trace_id"] == outer_d["span_id"]
    assert outer_d["attributes"] == {"queue": "q"}


def test_span_error_recorded_and_propagated():
    tr = Tracer()
    with pytest.raises(ValueError):
        with tr.span("boom"):
            raise ValueError("nope")
    (s,) = tr.recent()
    assert s["error"] == "ValueError: nope"


def test_ring_buffer_bounds_memory():
    tr = Tracer(capacity=4)
    for i in range(10):
        with tr.span(f"s{i}"):
            pass
    names = [s["name"] for s in tr.recent()]
    assert names == ["s6", "s7", "s8", "s9"]


def test_traced_decorator_nests_under_caller():
    tr = Tracer()

    @traced("child", tracer=tr)
    def work():
        return 42

    with tr.span("parent"):
        assert work() == 42
    child, parent = tr.recent()
    assert child["name"] == "child"
    assert child["parent_id"] == parent["span_id"]


def test_threads_do_not_share_span_stacks():
    import threading

    tr = Tracer()
    errs = []

    def worker(n):
        try:
            with tr.span(f"w{n}"):
                assert tr.current().name == f"w{n}"
        except Exception as e:  # pragma: no cover
            errs.append(e)

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(8)]
    [t.start() for t in threads]
    [t.join() for t in threads]
    assert not errs
    assert all(s["parent_id"] is None for s in tr.recent())


def test_reconcile_emits_spans_with_provider_children():
    """An end-to-end converge drives reconcile spans into the default
    tracer with provider.ensure_* children nested beneath them."""
    default_tracer.clear()
    cluster = Cluster(workers=1).start()
    try:
        region = "us-east-1"
        hostname = f"trc-0123456789abcdef.elb.{region}.amazonaws.com"
        cluster.cloud.elb.register_load_balancer("trc", hostname, region)
        cluster.kube.services.create(Service(
            metadata=ObjectMeta(
                name="trc", namespace="default",
                annotations={
                    AWS_LOAD_BALANCER_TYPE_ANNOTATION: "external",
                    AWS_GLOBAL_ACCELERATOR_MANAGED_ANNOTATION: "true",
                }),
            spec=ServiceSpec(type="LoadBalancer",
                             ports=[ServicePort(port=80)]),
            status=ServiceStatus(load_balancer=LoadBalancerStatus(
                ingress=[LoadBalancerIngress(hostname=hostname)])),
        ))
        wait_until(lambda: len(cluster.cloud.ga.list_accelerators()) == 1,
                   timeout=30.0, message="accelerator created")
    finally:
        cluster.shutdown()

    spans = default_tracer.recent()
    rec = [s for s in spans if s["name"] == "reconcile"
           and s["attributes"].get("key") == "default/trc"]
    assert rec, "no reconcile span for the service"
    ensure = [s for s in spans
              if s["name"] == "provider.ensure_global_accelerator_for_service"]
    assert ensure, "no provider child span"
    rec_ids = {s["span_id"] for s in rec}
    assert any(s["parent_id"] in rec_ids for s in ensure)
    ok = [s for s in rec if s["attributes"].get("outcome") == "success"]
    assert ok and all(s["duration_s"] >= 0 for s in spans)


def test_traces_endpoint_serves_recent_spans():
    default_tracer.clear()
    with default_tracer.span("endpoint-probe", kind="test"):
        pass
    server = HealthServer(port=0)
    server.start_background()
    try:
        url = (f"http://127.0.0.1:{server.port}/traces"
               "?name=endpoint-probe&limit=5")
        body = json.loads(urllib.request.urlopen(url).read())
    finally:
        server.shutdown()
    assert [s["name"] for s in body["spans"]] == ["endpoint-probe"]
    assert body["spans"][0]["attributes"] == {"kind": "test"}


def test_traces_endpoint_rejects_bad_limit_and_unknown_paths():
    import urllib.error

    server = HealthServer(port=0)
    server.start_background()
    base = f"http://127.0.0.1:{server.port}"
    try:
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(base + "/traces?limit=abc")
        assert e.value.code == 400
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(base + "/traces?limit=-5")
        assert e.value.code == 400
        # limit=0 is the "dump everything buffered" contract
        default_tracer.clear()
        for i in range(3):
            with default_tracer.span(f"dump{i}"):
                pass
        body = json.loads(urllib.request.urlopen(
            base + "/traces?limit=0&name=dump").read())
        assert len(body["spans"]) == 3
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(base + "/tracesfoo")
        assert e.value.code == 404
    finally:
        server.shutdown()


# -- span-leak regression (a raising provider-call child) ---------------


def test_child_span_raise_leaves_stack_clean_and_next_span_nests_right():
    """A provider-call child span whose body raises must be popped and
    recorded with ``error`` set — and the NEXT span opened on the same
    thread must nest under the still-open parent, not under the dead
    child (the nests-after-raise regression)."""
    tr = Tracer()
    with tr.span("parent") as parent:
        with pytest.raises(RuntimeError):
            with tr.span("provider.call"):
                raise RuntimeError("api exploded")
        assert tr.current() is parent, "stack leaked the dead child"
        with tr.span("after") as after:
            assert after.parent_id == parent.span_id
    spans = {s["name"]: s for s in tr.recent()}
    assert spans["provider.call"]["error"] == "RuntimeError: api exploded"
    assert spans["after"]["parent_id"] == spans["parent"]["span_id"]
    assert tr.current() is None


def test_base_exception_still_pops_and_records_error():
    """Worker teardown (BaseException, not Exception) must also pop
    AND record the span with its error set — the flight recorder's
    last spans before a crash are the ones that matter."""
    tr = Tracer()

    class Teardown(BaseException):
        pass

    with pytest.raises(Teardown):
        with tr.span("dying"):
            raise Teardown("killed")
    (s,) = tr.recent()
    assert s["error"] == "Teardown: killed"
    assert tr.current() is None


# -- cross-thread continuation (attach/detach) --------------------------


def test_attach_continues_trace_on_another_thread():
    import threading

    from aws_global_accelerator_controller_tpu.tracing import (
        new_context,
    )

    tr = Tracer()
    ctx = new_context("event", tracer=tr, key="default/x")
    assert ctx is not None
    got = {}

    def worker():
        with tr.attach(ctx):
            with tr.span("reconcile", key="default/x") as s:
                got["span"] = s

    t = threading.Thread(target=worker)
    t.start()
    t.join()
    s = got["span"]
    assert s.trace_id == ctx.trace_id
    assert s.parent_id == ctx.parent_span_id
    origin = [x for x in tr.recent() if x["name"] == "origin.event"]
    assert origin and origin[0]["span_id"] == ctx.parent_span_id
    # the two spans ran on different OS threads: the continuation
    # provably crossed a thread
    tids = {x["tid"] for x in tr.recent()}
    assert len(tids) == 2


def test_attach_detach_concurrent_no_crosstalk(race_detectors):
    """Two workers concurrently attach/detach the SAME shared context
    interleaved with their own private traces: no span may end up with
    another trace's id (the thread-local continuation contract), and
    fold links must reference every contributing trace id."""
    import threading

    from aws_global_accelerator_controller_tpu.tracing import (
        fold_link,
        new_context,
    )

    tr = Tracer(capacity=8192)
    shared = new_context("event", tracer=tr, key="shared")
    errs = []

    def worker(n):
        try:
            for i in range(200):
                with tr.attach(shared):
                    with tr.span(f"shared-w{n}") as s:
                        assert s.trace_id == shared.trace_id
                own = new_context("event", tracer=tr, key=f"own-{n}-{i}")
                with tr.attach(own):
                    with tr.span(f"own-w{n}") as s:
                        assert s.trace_id == own.trace_id
                        assert s.trace_id != shared.trace_id
        except Exception as e:  # pragma: no cover
            errs.append(e)

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(2)]
    [t.start() for t in threads]
    [t.join() for t in threads]
    assert not errs
    for s in tr.recent(limit=0):
        if s["name"].startswith("shared-"):
            assert s["trace_id"] == shared.trace_id
        elif s["name"].startswith("own-"):
            assert s["trace_id"] != shared.trace_id
    # fold links: every contributing trace id is recorded on both
    # contexts and the link span
    a = new_context("event", tracer=tr, key="a")
    b = new_context("event", tracer=tr, key="b")
    fold_link(a, b, tracer=tr)
    folds = [s for s in tr.recent(limit=0) if s["name"] == "fold"]
    assert folds and folds[-1]["trace_id"] == a.trace_id
    assert folds[-1]["links"] == [b.trace_id]
    assert b.trace_id in a.links and a.trace_id in b.links


def test_disabled_tracing_mints_no_contexts_and_records_nothing():
    from aws_global_accelerator_controller_tpu import tracing

    tr = Tracer()
    tracing.set_enabled(False)
    try:
        assert tracing.new_context("event", tracer=tr) is None
        with tr.span("ghost") as s:
            s.attributes["x"] = 1  # dummy span accepts writes
        assert tr.recent() == []
    finally:
        tracing.set_enabled(True)


# -- workqueue trace sidecar -------------------------------------------


def test_workqueue_carries_and_merges_trace_contexts():
    from aws_global_accelerator_controller_tpu.kube.workqueue import (
        RateLimitingQueue,
    )
    from aws_global_accelerator_controller_tpu.tracing import (
        new_context,
    )

    tr = Tracer()
    q = RateLimitingQueue(name="q")
    try:
        ctx1 = new_context("event", tracer=tr, key="k")
        q.add("k", klass="interactive", ctx=ctx1)
        # dedup merge: the second event's trace links into the pending
        ctx2 = new_context("event", tracer=tr, key="k")
        q.add("k", klass="interactive", ctx=ctx2)
        assert ctx2.trace_id in ctx1.links
        assert ctx1.trace_id in ctx2.links
        item, _ = q.get()
        assert item == "k"
        assert q.claimed_trace("k") is ctx1
        assert [h[0] for h in ctx1.hops][:2] == ["event", "queued"]
        q.done("k")
        assert q.claimed_trace("k") is None
        # requeue re-installs the same context: a second queued hop
        q.add_after("k", 0.0, klass="keep", ctx=ctx1)
        assert q.pending_trace("k") is ctx1
        assert [h[0] for h in ctx1.hops].count("queued") == 2
    finally:
        q.shutdown()


# -- convergence ledger -------------------------------------------------


def test_ledger_stage_breakdown_and_percentiles():
    from aws_global_accelerator_controller_tpu.metrics import Registry
    from aws_global_accelerator_controller_tpu.tracing import (
        ConvergenceLedger,
        TraceContext,
    )

    ctx = TraceContext(trace_id=7, origin="event", parent_span_id=7)
    t = 100.0
    for stage, dt in (("event", 0.0), ("queued", 0.001),
                      ("claimed", 0.004), ("planned", 0.010),
                      ("inflight", 0.003), ("flushed", 0.020),
                      ("converged", 0.002)):
        t += dt
        ctx.hop(stage, now=t, wall=t)
    ledger = ConvergenceLedger()
    reg = Registry()
    rec = ledger.record("ctrl", "default/x", ctx, registry=reg)
    st = rec["stages"]
    assert st["queued"] == pytest.approx(0.005)    # enqueue + wait
    assert st["planned"] == pytest.approx(0.010)
    assert st["coalesced"] == pytest.approx(0.003)
    assert st["inflight"] == pytest.approx(0.020)
    assert st["baked"] == pytest.approx(0.002)
    assert rec["total_s"] == pytest.approx(0.040)
    # stage histograms got fed, with the trace id as exemplar
    assert reg.histogram_count("stage_seconds",
                               {"stage": "inflight",
                                "controller": "ctrl"}) == 1
    assert 'trace_id=7' in reg.render()
    pct = ledger.percentiles("ctrl")
    assert pct["inflight"]["p50_s"] == pytest.approx(0.020)
    assert pct["total"]["count"] == 1
    # snapshot filters
    assert ledger.snapshot(key="default/x")[0]["trace_id"] == 7
    assert ledger.snapshot(key="nope") == []


# -- chrome trace-event export ------------------------------------------


def test_chrome_serializer_shapes():
    from aws_global_accelerator_controller_tpu.tracing import (
        to_chrome_events,
    )

    tr = Tracer()
    with tr.span("outer", key="default/x"):
        with tr.span("inner"):
            pass
    events = to_chrome_events(tr.recent())
    assert {e["name"] for e in events} == {"outer", "inner"}
    for e in events:
        assert e["ph"] == "X"
        assert e["dur"] >= 1.0
        assert isinstance(e["ts"], float)
        assert e["args"]["span_id"]
    outer = [e for e in events if e["name"] == "outer"][0]
    inner = [e for e in events if e["name"] == "inner"][0]
    assert outer["tid"] == inner["tid"], "one lane per trace"


def test_traces_endpoint_filters_and_chrome_format():
    import urllib.error

    default_tracer.clear()
    with default_tracer.span("reconcile", queue="qa", key="default/a"):
        pass
    with default_tracer.span("reconcile", queue="qb", key="default/b"):
        import time as _t
        _t.sleep(0.02)
    server = HealthServer(port=0)
    server.start_background()
    base = f"http://127.0.0.1:{server.port}"
    try:
        got = json.loads(urllib.request.urlopen(
            base + "/traces?key=default/a").read())
        assert [s["attributes"]["key"] for s in got["spans"]] \
            == ["default/a"]
        got = json.loads(urllib.request.urlopen(
            base + "/traces?queue=qb").read())
        assert [s["attributes"]["queue"] for s in got["spans"]] == ["qb"]
        got = json.loads(urllib.request.urlopen(
            base + "/traces?min_duration=0.01").read())
        assert [s["attributes"]["key"] for s in got["spans"]] \
            == ["default/b"]
        got = json.loads(urllib.request.urlopen(
            base + "/traces?format=chrome&key=default/b").read())
        assert got["traceEvents"] and \
            got["traceEvents"][0]["ph"] == "X"
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(base + "/traces?format=jaeger")
        assert e.value.code == 400
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(base + "/traces?min_duration=abc")
        assert e.value.code == 400
    finally:
        server.shutdown()


def test_traces_ledger_endpoint():
    from aws_global_accelerator_controller_tpu.tracing import (
        TraceContext,
        default_ledger,
    )

    default_ledger.clear()
    ctx = TraceContext(trace_id=99, origin="event", parent_span_id=99)
    for i, stage in enumerate(("event", "queued", "claimed",
                               "converged")):
        ctx.hop(stage, now=10.0 + i * 0.01, wall=10.0 + i * 0.01)
    default_ledger.record("qx", "default/led", ctx)
    server = HealthServer(port=0)
    server.start_background()
    base = f"http://127.0.0.1:{server.port}"
    try:
        got = json.loads(urllib.request.urlopen(
            base + "/traces/ledger?key=default/led").read())
        assert got["records"][0]["trace_id"] == 99
        assert "queued" in got["records"][0]["stages"]
        assert "total" in got["percentiles"]
        got = json.loads(urllib.request.urlopen(
            base + "/traces/ledger?controller=nope").read())
        assert got["records"] == []
    finally:
        server.shutdown()
        default_ledger.clear()
