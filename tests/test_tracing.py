"""Tracing subsystem: span nesting, ring buffer, reconcile-path spans,
and the /traces endpoint."""
import json
import sys
import urllib.request

import pytest

from aws_global_accelerator_controller_tpu.metrics import HealthServer
from aws_global_accelerator_controller_tpu.tracing import (
    Tracer,
    default_tracer,
    traced,
)

sys.path.insert(0, "tests")
from harness import Cluster, wait_until  # noqa: E402

from aws_global_accelerator_controller_tpu.apis import (  # noqa: E402
    AWS_GLOBAL_ACCELERATOR_MANAGED_ANNOTATION,
    AWS_LOAD_BALANCER_TYPE_ANNOTATION,
)
from aws_global_accelerator_controller_tpu.kube.objects import (  # noqa: E402
    LoadBalancerIngress,
    LoadBalancerStatus,
    ObjectMeta,
    Service,
    ServicePort,
    ServiceSpec,
    ServiceStatus,
)


def test_span_nesting_and_trace_ids():
    tr = Tracer()
    with tr.span("outer", queue="q") as outer:
        with tr.span("inner") as inner:
            assert tr.current() is inner
        assert tr.current() is outer
    spans = tr.recent()
    assert [s["name"] for s in spans] == ["inner", "outer"]
    inner_d, outer_d = spans
    assert inner_d["parent_id"] == outer_d["span_id"]
    assert inner_d["trace_id"] == outer_d["trace_id"] == outer_d["span_id"]
    assert outer_d["attributes"] == {"queue": "q"}


def test_span_error_recorded_and_propagated():
    tr = Tracer()
    with pytest.raises(ValueError):
        with tr.span("boom"):
            raise ValueError("nope")
    (s,) = tr.recent()
    assert s["error"] == "ValueError: nope"


def test_ring_buffer_bounds_memory():
    tr = Tracer(capacity=4)
    for i in range(10):
        with tr.span(f"s{i}"):
            pass
    names = [s["name"] for s in tr.recent()]
    assert names == ["s6", "s7", "s8", "s9"]


def test_traced_decorator_nests_under_caller():
    tr = Tracer()

    @traced("child", tracer=tr)
    def work():
        return 42

    with tr.span("parent"):
        assert work() == 42
    child, parent = tr.recent()
    assert child["name"] == "child"
    assert child["parent_id"] == parent["span_id"]


def test_threads_do_not_share_span_stacks():
    import threading

    tr = Tracer()
    errs = []

    def worker(n):
        try:
            with tr.span(f"w{n}"):
                assert tr.current().name == f"w{n}"
        except Exception as e:  # pragma: no cover
            errs.append(e)

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(8)]
    [t.start() for t in threads]
    [t.join() for t in threads]
    assert not errs
    assert all(s["parent_id"] is None for s in tr.recent())


def test_reconcile_emits_spans_with_provider_children():
    """An end-to-end converge drives reconcile spans into the default
    tracer with provider.ensure_* children nested beneath them."""
    default_tracer.clear()
    cluster = Cluster(workers=1).start()
    try:
        region = "us-east-1"
        hostname = f"trc-0123456789abcdef.elb.{region}.amazonaws.com"
        cluster.cloud.elb.register_load_balancer("trc", hostname, region)
        cluster.kube.services.create(Service(
            metadata=ObjectMeta(
                name="trc", namespace="default",
                annotations={
                    AWS_LOAD_BALANCER_TYPE_ANNOTATION: "external",
                    AWS_GLOBAL_ACCELERATOR_MANAGED_ANNOTATION: "true",
                }),
            spec=ServiceSpec(type="LoadBalancer",
                             ports=[ServicePort(port=80)]),
            status=ServiceStatus(load_balancer=LoadBalancerStatus(
                ingress=[LoadBalancerIngress(hostname=hostname)])),
        ))
        wait_until(lambda: len(cluster.cloud.ga.list_accelerators()) == 1,
                   timeout=30.0, message="accelerator created")
    finally:
        cluster.shutdown()

    spans = default_tracer.recent()
    rec = [s for s in spans if s["name"] == "reconcile"
           and s["attributes"].get("key") == "default/trc"]
    assert rec, "no reconcile span for the service"
    ensure = [s for s in spans
              if s["name"] == "provider.ensure_global_accelerator_for_service"]
    assert ensure, "no provider child span"
    rec_ids = {s["span_id"] for s in rec}
    assert any(s["parent_id"] in rec_ids for s in ensure)
    ok = [s for s in rec if s["attributes"].get("outcome") == "success"]
    assert ok and all(s["duration_s"] >= 0 for s in spans)


def test_traces_endpoint_serves_recent_spans():
    default_tracer.clear()
    with default_tracer.span("endpoint-probe", kind="test"):
        pass
    server = HealthServer(port=0)
    server.start_background()
    try:
        url = (f"http://127.0.0.1:{server.port}/traces"
               "?name=endpoint-probe&limit=5")
        body = json.loads(urllib.request.urlopen(url).read())
    finally:
        server.shutdown()
    assert [s["name"] for s in body["spans"]] == ["endpoint-probe"]
    assert body["spans"][0]["attributes"] == {"kind": "test"}


def test_traces_endpoint_rejects_bad_limit_and_unknown_paths():
    import urllib.error

    server = HealthServer(port=0)
    server.start_background()
    base = f"http://127.0.0.1:{server.port}"
    try:
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(base + "/traces?limit=abc")
        assert e.value.code == 400
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(base + "/traces?limit=-5")
        assert e.value.code == 400
        # limit=0 is the "dump everything buffered" contract
        default_tracer.clear()
        for i in range(3):
            with default_tracer.span(f"dump{i}"):
                pass
        body = json.loads(urllib.request.urlopen(
            base + "/traces?limit=0&name=dump").read())
        assert len(body["spans"]) == 3
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(base + "/tracesfoo")
        assert e.value.code == 404
    finally:
        server.shutdown()
