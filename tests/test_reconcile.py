"""Reconcile engine dispatch tests.

Covers the Result/error dispatch table of reference
pkg/reconcile/reconcile.go:70-89 against a real queue -- the reference has
no such tests (SURVEY.md §4 notes the gap); SURVEY.md §7 step 2 calls for
them.
"""
from aws_global_accelerator_controller_tpu.errors import (
    NotFoundError,
    new_no_retry_errorf,
)
from aws_global_accelerator_controller_tpu.kube.workqueue import (
    ItemExponentialFailureRateLimiter,
    RateLimitingQueue,
)
from aws_global_accelerator_controller_tpu.reconcile import (
    Result,
    process_next_work_item,
)


class FakeObj:
    def __init__(self, key):
        self.k = key
        self.copied = False

    def deep_copy(self):
        cp = FakeObj(self.k)
        cp.copied = True
        return cp


def make_queue():
    return RateLimitingQueue(
        rate_limiter=ItemExponentialFailureRateLimiter(0.001, 0.05))


def run_one(queue, key_to_obj, delete=None, upsert=None):
    return process_next_work_item(
        queue, key_to_obj,
        delete or (lambda key: Result()),
        upsert or (lambda obj: Result()),
        get_timeout=1.0)


def test_success_forgets():
    q = make_queue()
    q.add("ns/a")
    seen = []
    run_one(q, lambda k: FakeObj(k), upsert=lambda o: seen.append(o) or Result())
    q_len_after = len(q)
    assert seen and seen[0].copied, "process funcs must receive a deep copy"
    assert q_len_after == 0
    assert q.num_requeues("ns/a") == 0


def test_not_found_routes_to_delete():
    q = make_queue()
    q.add("ns/gone")
    calls = []

    def key_to_obj(key):
        raise NotFoundError("Service", key)

    run_one(q, key_to_obj, delete=lambda key: calls.append(key) or Result())
    assert calls == ["ns/gone"]


def test_error_requeues_rate_limited():
    q = make_queue()
    q.add("ns/err")

    def upsert(obj):
        raise RuntimeError("transient AWS error")

    run_one(q, lambda k: FakeObj(k), upsert=upsert)
    assert q.num_requeues("ns/err") == 1
    item, shutdown = q.get(timeout=1.0)
    assert item == "ns/err" and not shutdown


def test_no_retry_error_drops():
    q = make_queue()
    q.add("bad//key")

    def upsert(obj):
        raise new_no_retry_errorf("invalid resource key")

    run_one(q, lambda k: FakeObj(k), upsert=upsert)
    item, _ = q.get(timeout=0.2)
    assert item is None, "NoRetryError must not requeue"


def test_requeue_after_forgets_then_delays():
    q = make_queue()
    q.add("ns/later")
    run_one(q, lambda k: FakeObj(k), upsert=lambda o: Result(requeue_after=0.05))
    assert q.num_requeues("ns/later") == 0  # Forget was called
    item, _ = q.get(timeout=1.0)
    assert item == "ns/later"


def test_requeue_rate_limited():
    q = make_queue()
    q.add("ns/again")
    run_one(q, lambda k: FakeObj(k), upsert=lambda o: Result(requeue=True))
    assert q.num_requeues("ns/again") == 1
    item, _ = q.get(timeout=1.0)
    assert item == "ns/again"


def test_shutdown_returns_false():
    q = make_queue()
    q.shutdown()
    assert process_next_work_item(
        q, lambda k: FakeObj(k), lambda k: Result(), lambda o: Result()) is False


def test_process_delete_error_requeues():
    q = make_queue()
    q.add("ns/gone")

    def key_to_obj(key):
        raise NotFoundError("Service", key)

    def delete(key):
        raise RuntimeError("cleanup failed")

    run_one(q, key_to_obj, delete=delete)
    item, _ = q.get(timeout=1.0)
    assert item == "ns/gone", "failed delete must be retried"


def test_nested_cause_no_retry_error_drops():
    """A NoRetryError buried under ``raise ... from`` layers still
    takes the drop path — the errors.As-over-Unwrap walk, end to end
    through the dispatch table."""
    q = make_queue()
    q.add("ns/nested")

    def upsert(obj):
        try:
            try:
                raise new_no_retry_errorf("invalid key shape")
            except Exception as inner:
                raise RuntimeError("ensure failed") from inner
        except Exception as mid:
            raise RuntimeError("sync failed") from mid

    run_one(q, lambda k: FakeObj(k), upsert=upsert)
    item, _ = q.get(timeout=0.2)
    assert item is None, "nested NoRetryError must not requeue"
    assert q.num_requeues("ns/nested") == 0


def test_retry_budget_exhaustion_parks_with_add_after():
    """An error carrying a retry_after hint (the resilience layer's
    budget/deadline/circuit errors) takes Forget + AddAfter, not the
    rate limiter: the failure count resets and the key reappears only
    after the hinted delay."""
    from aws_global_accelerator_controller_tpu.resilience import (
        RetryBudgetExceededError,
    )

    q = make_queue()
    q.add("ns/browned-out")

    def upsert(obj):
        raise RetryBudgetExceededError("describe_accelerator", 4, 0.05)

    run_one(q, lambda k: FakeObj(k), upsert=upsert)
    assert q.num_requeues("ns/browned-out") == 0, \
        "park path must Forget (the in-call budget was the backoff)"
    item, _ = q.get(timeout=1.0)
    assert item == "ns/browned-out", "parked key must come back"


def test_retry_after_hint_beats_rate_limited_requeue():
    """Precedence: a hint-carrying error wrapped in a plain error still
    parks (hint wins over add_rate_limited); a plain error without a
    hint takes the rate limiter."""
    from aws_global_accelerator_controller_tpu.resilience import (
        CircuitOpenError,
    )

    q = make_queue()
    q.add("ns/mixed")

    def upsert(obj):
        try:
            raise CircuitOpenError("us-west-2", 0.04)
        except Exception as inner:
            raise RuntimeError("ensure failed") from inner

    run_one(q, lambda k: FakeObj(k), upsert=upsert)
    assert q.num_requeues("ns/mixed") == 0          # parked, not limited

    def plain(obj):
        raise RuntimeError("no hint")

    # the parked key reappears after the hint delay; failing it with a
    # hint-less error takes the rate limiter
    run_one(q, lambda k: FakeObj(k), upsert=plain)
    assert q.num_requeues("ns/mixed") == 1          # rate-limited path


def test_requeue_count_bounds_under_permanent_failure():
    """A permanently failing key keeps cycling through the rate
    limiter: the failure count grows one per sync (no hot loop — each
    cycle waits out the limiter delay) and the per-item delay is
    capped at the limiter's max."""
    limiter = ItemExponentialFailureRateLimiter(0.001, 0.01)
    q = RateLimitingQueue(rate_limiter=limiter)
    q.add("ns/doomed")

    def upsert(obj):
        raise RuntimeError("permanently broken")

    for expected in range(1, 7):
        # run_one pops the (delayed) key, fails it, re-adds it
        # rate-limited: exactly one failure-count step per cycle
        run_one(q, lambda k: FakeObj(k), upsert=upsert)
        assert q.num_requeues("ns/doomed") == expected
    # the NEXT delay (failures=6: base * 2^6 = 64ms uncapped) must cap
    # at the limiter max — the bound that keeps a permanent failure
    # from backing off into oblivion or hot-looping
    assert limiter.when("ns/doomed") <= 0.01 + 1e-9, \
        "backoff must cap at the limiter max, not grow unboundedly"
