"""ShardedTemporalPlanner (dp x sp mesh) vs the unsharded temporal model.

The sharded program — ring attention over 'seq', groups over 'data' —
must be numerically the SAME model: same forward weights, same training
trajectory (up to float tolerance), or the multi-chip path silently
trains a different function than the single-chip one.
"""
import jax
import numpy as np
import pytest
from jax.sharding import Mesh

from aws_global_accelerator_controller_tpu.models.temporal import (
    TemporalTrafficModel,
    synthetic_window,
)
from aws_global_accelerator_controller_tpu.parallel import (
    ShardedTemporalPlanner,
)


def _mesh(seq, data):
    devs = np.asarray(jax.devices()[:seq * data]).reshape(data, seq)
    return Mesh(devs, axis_names=("data", "seq"))


def _setup(t=8, groups=4, endpoints=4, seed=0):
    model = TemporalTrafficModel(feature_dim=8, embed_dim=16,
                                 hidden_dim=32, attention="reference")
    params = model.init_params(jax.random.PRNGKey(seed))
    window, batch = synthetic_window(jax.random.PRNGKey(seed + 1),
                                     steps=t, groups=groups,
                                     endpoints=endpoints)
    return model, params, window, batch


@pytest.mark.parametrize("seq,data", [(2, 1), (4, 2), (8, 1), (2, 4)])
def test_sharded_forward_matches_unsharded(seq, data):
    """Scores agree to float tolerance; the integer weight plan may
    flip a single unit where the sharded softmax merge (per-shard
    (o, m, l) folded by the flash recurrence) rounds a quantization
    boundary differently than the dense one-shot softmax."""
    model, params, window, batch = _setup(t=8, groups=4, seed=seq * 10
                                          + data)
    planner = ShardedTemporalPlanner(model, _mesh(seq, data))
    sp = planner.shard_params(params)
    sw = planner.shard_window(window)
    got_scores = np.asarray(jax.jit(
        lambda p, w: model.scores_last(
            p, w, attend_last=planner._last_attend),
        in_shardings=(planner.param_sharding,
                      planner.window_sharding))(sp, sw))
    want_scores = np.asarray(model.scores_last(params, window))
    np.testing.assert_allclose(got_scores, want_scores, rtol=1e-4,
                               atol=1e-5)
    got = np.asarray(planner.forward(sp, sw, batch.mask))
    want = np.asarray(jax.jit(model.forward)(params, window,
                                             batch.mask))
    assert np.abs(got.astype(np.int64)
                  - want.astype(np.int64)).max() <= 1
    assert (got == want).mean() >= 0.9


def test_sharded_training_tracks_unsharded():
    """5 training steps sharded vs unsharded: same loss trajectory."""
    model, params, window, batch = _setup(t=8, groups=4, seed=3)
    planner = ShardedTemporalPlanner(model, _mesh(4, 2))
    sp = planner.shard_params(params)
    s_opt = model.init_opt_state(sp)
    u_opt = model.init_opt_state(params)
    step_u = jax.jit(model.train_step)
    sw = planner.shard_window(window)
    sb = planner.shard_batch(batch)
    for i in range(5):
        sp, s_opt, s_loss = planner.train_step(sp, s_opt, sw, sb)
        params, u_opt, u_loss = step_u(params, u_opt, window, batch)
        # bf16 params: sharded vs unsharded reduction orders round
        # updates differently, so trajectories drift a few 1e-4/step
        np.testing.assert_allclose(float(s_loss), float(u_loss),
                                   rtol=2e-3, atol=2e-4,
                                   err_msg=f"step {i}")
    # parameters converged to the same place.  atol = lr * steps:
    # near-zero params (fresh biases) can see bf16 reduction drift
    # flip an update's SIGN, so the honest absolute bound is the
    # 5-step Adam walk itself (1e-3 * 5), not a fraction of it
    for name in params:
        np.testing.assert_allclose(
            np.asarray(sp[name], dtype=np.float32),
            np.asarray(params[name], dtype=np.float32),
            rtol=2e-2, atol=5e-3, err_msg=f"param {name}")


def test_sharded_training_reduces_loss_flash_local():
    """The ring(local='flash') forward composes with the ring backward:
    training still learns on the dp x sp mesh."""
    model, params, window, batch = _setup(t=16, groups=2, endpoints=4,
                                          seed=7)
    planner = ShardedTemporalPlanner(model, _mesh(2, 2), local="flash")
    sp = planner.shard_params(params)
    opt = model.init_opt_state(sp)
    sw = planner.shard_window(window)
    sb = planner.shard_batch(batch)
    first = None
    for _ in range(15):
        sp, opt, loss = planner.train_step(sp, opt, sw, sb)
        if first is None:
            first = float(loss)
    assert float(loss) < first
    assert np.isfinite(float(loss))


def test_local_auto_resolves_off_tpu():
    model, params, window, batch = _setup()
    planner = ShardedTemporalPlanner(model, _mesh(2, 1))
    # attention='reference' (and any off-TPU 'flash') -> einsum local
    got = planner.forward(planner.shard_params(params),
                          planner.shard_window(window), batch.mask)
    assert got.shape == batch.mask.shape


def test_sharded_last_supervision_training_tracks_unsharded():
    """Default (last) supervision trains through the O(T) last-query
    path on BOTH sides; trajectories agree like the full-attention
    law did."""
    model, params, window, batch = _setup(t=8, groups=4, seed=11)
    planner = ShardedTemporalPlanner(model, _mesh(4, 2))
    sp = planner.shard_params(params)
    s_opt = model.init_opt_state(sp)
    u_opt = model.init_opt_state(params)
    step_u = jax.jit(model.train_step)
    sw = planner.shard_window(window)
    sb = planner.shard_batch(batch)
    for i in range(5):
        sp, s_opt, s_loss = planner.train_step(sp, s_opt, sw, sb)
        params, u_opt, u_loss = step_u(params, u_opt, window, batch)
        np.testing.assert_allclose(float(s_loss), float(u_loss),
                                   rtol=2e-3, atol=2e-4,
                                   err_msg=f"step {i}")


def test_sharded_sequence_supervision_tracks_unsharded():
    """Sequence supervision: per-step targets [T, G, E] shard over
    (seq, data); the sharded step trains THROUGH ring attention and
    tracks the dense sequence-supervised oracle."""
    model = TemporalTrafficModel(feature_dim=8, embed_dim=16,
                                 hidden_dim=32, attention="reference",
                                 supervision="sequence")
    params = model.init_params(jax.random.PRNGKey(21))
    window, batch = synthetic_window(jax.random.PRNGKey(22), steps=8,
                                     groups=4, endpoints=4,
                                     per_step=True)
    planner = ShardedTemporalPlanner(model, _mesh(4, 2))
    sp = planner.shard_params(params)
    s_opt = model.init_opt_state(sp)
    u_opt = model.init_opt_state(params)
    step_u = jax.jit(model.train_step)
    sw = planner.shard_window(window)
    sb = planner.shard_batch(batch)
    # target really lives sharded over (seq, data)
    tshards = sb.target.addressable_shards
    assert {s_.data.shape for s_ in tshards} == {(2, 2, 4)}
    for i in range(5):
        sp, s_opt, s_loss = planner.train_step(sp, s_opt, sw, sb)
        params, u_opt, u_loss = step_u(params, u_opt, window, batch)
        np.testing.assert_allclose(float(s_loss), float(u_loss),
                                   rtol=2e-3, atol=2e-4,
                                   err_msg=f"step {i}")
    for name in params:
        np.testing.assert_allclose(
            np.asarray(sp[name], dtype=np.float32),
            np.asarray(params[name], dtype=np.float32),
            rtol=2e-2, atol=2e-3, err_msg=name)


def test_make_last_attention_matches_reference():
    """The shard_map last-query attend (per-shard stats + flash-merge
    over the seq axis) equals the dense last-row oracle."""
    from aws_global_accelerator_controller_tpu.models.temporal import (
        attention_last_reference,
    )
    from aws_global_accelerator_controller_tpu.parallel import (
        make_last_attention,
    )

    mesh = _mesh(4, 2)
    ks = jax.random.split(jax.random.PRNGKey(31), 3)
    q, k, v = (jax.random.normal(kk, (16, 8, 16)) for kk in ks)
    fn = make_last_attention(mesh, "seq", "data")
    got = np.asarray(fn(q[-1], k, v))
    want = np.asarray(attention_last_reference(q[-1], k, v))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_sharded_sequence_remat_matches_plain():
    """remat composes with the sharded sequence step (the model's
    scores_seq carries the checkpoint; the planner inherits it):
    bitwise-identical losses."""
    kw = dict(feature_dim=8, embed_dim=16, hidden_dim=32,
              attention="reference", supervision="sequence")
    plain = TemporalTrafficModel(**kw)
    remat = TemporalTrafficModel(remat=True, **kw)
    params = plain.init_params(jax.random.PRNGKey(41))
    window, batch = synthetic_window(jax.random.PRNGKey(42), steps=8,
                                     groups=4, endpoints=4,
                                     per_step=True)
    mesh = _mesh(4, 2)
    pl_p = ShardedTemporalPlanner(plain, mesh)
    pl_r = ShardedTemporalPlanner(remat, mesh)
    p1, o1 = pl_p.shard_params(params), plain.init_opt_state(params)
    p2, o2 = pl_r.shard_params(params), remat.init_opt_state(params)
    sw1, sb1 = pl_p.shard_window(window), pl_p.shard_batch(batch)
    sw2, sb2 = pl_r.shard_window(window), pl_r.shard_batch(batch)
    for _ in range(3):
        p1, o1, l1 = pl_p.train_step(p1, o1, sw1, sb1)
        p2, o2, l2 = pl_r.train_step(p2, o2, sw2, sb2)
        assert float(l1) == float(l2)


def test_make_last_attention_without_head_axis():
    """head_axis=None (heads replicated): the 1-D seq-only mesh path."""
    from aws_global_accelerator_controller_tpu.models.temporal import (
        attention_last_reference,
    )
    from aws_global_accelerator_controller_tpu.parallel import (
        make_last_attention,
    )
    from aws_global_accelerator_controller_tpu.parallel.ring import (
        make_mesh_1d,
    )

    mesh = make_mesh_1d(8, "seq")
    ks = jax.random.split(jax.random.PRNGKey(51), 3)
    q, k, v = (jax.random.normal(kk, (16, 4, 8)) for kk in ks)
    fn = make_last_attention(mesh, "seq")
    got = np.asarray(fn(q[-1], k, v))
    want = np.asarray(attention_last_reference(q[-1], k, v))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_zigzag_sequence_supervision_tracks_unsharded():
    """layout='zigzag': the balanced causal ring (half-block steps on
    every device) trains the SAME function — loss trajectory and final
    params track the dense sequence-supervised oracle on unpermuted
    data, with the planner handling window/target placement."""
    model = TemporalTrafficModel(feature_dim=8, embed_dim=16,
                                 hidden_dim=32, attention="reference",
                                 supervision="sequence")
    params = model.init_params(jax.random.PRNGKey(51))
    window, batch = synthetic_window(jax.random.PRNGKey(52), steps=16,
                                     groups=4, endpoints=4,
                                     per_step=True)
    planner = ShardedTemporalPlanner(model, _mesh(4, 2),
                                     layout="zigzag")
    sp = planner.shard_params(params)
    s_opt = model.init_opt_state(sp)
    u_opt = model.init_opt_state(params)
    step_u = jax.jit(model.train_step)
    sw = planner.shard_window(window)
    sb = planner.shard_batch(batch)
    for i in range(5):
        sp, s_opt, s_loss = planner.train_step(sp, s_opt, sw, sb)
        params, u_opt, u_loss = step_u(params, u_opt, window, batch)
        np.testing.assert_allclose(float(s_loss), float(u_loss),
                                   rtol=2e-3, atol=2e-4,
                                   err_msg=f"step {i}")
    for name in params:
        # b2's true gradient is ~0 (softmax CE is invariant to a
        # uniform score shift), so Adam normalises pure association
        # noise into full-lr steps — its trajectory is noise in BOTH
        # runs (measured: contiguous vs dense has the same ~0.3
        # relative error on b2 at absmax 1e-4).  Bound it by the
        # worst-case drift (5 steps × lr both directions) instead.
        atol = 1.2e-2 if name == "b2" else 2e-3
        np.testing.assert_allclose(
            np.asarray(sp[name], dtype=np.float32),
            np.asarray(params[name], dtype=np.float32),
            rtol=2e-2, atol=atol, err_msg=name)


def test_zigzag_serving_forward_matches_contiguous():
    """Serving under zigzag: the true final timestep lives at the end
    of shard 0's block, and the planner's forward must find it — the
    weight plan equals the contiguous planner's on the same data."""
    model = TemporalTrafficModel(feature_dim=8, embed_dim=16,
                                 hidden_dim=32, attention="reference",
                                 supervision="sequence")
    params = model.init_params(jax.random.PRNGKey(61))
    window, batch = synthetic_window(jax.random.PRNGKey(62), steps=16,
                                     groups=4, endpoints=4,
                                     per_step=True)
    zig = ShardedTemporalPlanner(model, _mesh(4, 2), layout="zigzag")
    con = ShardedTemporalPlanner(model, _mesh(4, 2))
    got = np.asarray(zig.forward(
        zig.shard_params(params), zig.shard_window(window),
        batch.mask))
    want = np.asarray(con.forward(
        con.shard_params(params), con.shard_window(window),
        batch.mask))
    np.testing.assert_allclose(got, want, atol=1)  # integer plan ±1


def test_zigzag_requires_sequence_supervision():
    model = TemporalTrafficModel(feature_dim=8, embed_dim=16,
                                 hidden_dim=32, supervision="last")
    with pytest.raises(ValueError, match="sequence"):
        ShardedTemporalPlanner(model, _mesh(2, 1), layout="zigzag")
