"""Safe-rollout state machine + engine units (rollout/).

The resumability matrix: because all ramp state is durable (status /
state annotation) and :func:`rollout.machine.advance` is pure, a crash
is exactly "serialize the state, forget everything else, re-advance" —
so these tests kill/restart the machine at EVERY boundary (after a
transition persisted, before its weights landed; after the weights
landed, before the next turn; mid-step with partial convergence) by
round-tripping the state through its wire encoding between turns, and
assert the weight WRITES stay monotone with zero duplicates.
"""
import json

import pytest

from aws_global_accelerator_controller_tpu.metrics import Registry
from aws_global_accelerator_controller_tpu.rollout import (
    HEALTH_DEGRADED,
    HEALTH_FAILED,
    HEALTHY,
    Health,
    PHASE_COMPLETED,
    PHASE_PROGRESSING,
    PHASE_ROLLED_BACK,
    PHASE_ROLLING_BACK,
    RolloutEngine,
    RolloutSpec,
    RolloutState,
    StaleRolloutTokenError,
    advance,
    parse_spec,
    planned_weights,
    rollout_active,
)
from aws_global_accelerator_controller_tpu.apis import (
    ROLLOUT_ABORT_ANNOTATION,
    ROLLOUT_INTERVAL_ANNOTATION,
    ROLLOUT_STEPS_ANNOTATION,
)

SPEC = RolloutSpec(steps=(5, 25, 50, 100), interval=10.0)
E1 = "arn:aws:elb:eu-west-1:1:loadbalancer/net/one/aaaa"
E2 = "arn:aws:elb:eu-west-1:1:loadbalancer/net/two/bbbb"


def crash(state):
    """A crash is: keep only the durable encoding."""
    if state is None:
        return None
    return RolloutState.from_dict(
        json.loads(json.dumps(state.to_dict())))


class World:
    """A tiny cloud: applies writes, tracks every write issued so the
    monotonicity / zero-duplicate assertions have a full history."""

    def __init__(self, observed=None):
        self.observed = dict(observed or {})
        self.writes = []

    def apply(self, outcome):
        if outcome.write is not None:
            self.writes.append(dict(outcome.write))
            self.observed.update(outcome.write)


def drive(spec, desired, world, state=None, now=0.0, token=0,
          health=HEALTHY, crash_every_turn=False, max_turns=64):
    """Run turns until the machine settles (requeue 0 and no state
    change); returns (final state, now).  ``crash_every_turn`` round
    trips the state through its wire encoding between turns."""
    for _ in range(max_turns):
        out = advance(spec, state, desired, dict(world.observed), now,
                      token, health=health)
        if out.state is not None:
            state = out.state         # persisted FIRST...
            if crash_every_turn:
                state = crash(state)
        world.apply(out)              # ...then the weights land
        if out.requeue_after <= 0:
            return state, now
        now += out.requeue_after
    raise AssertionError("machine never settled")


# ---------------------------------------------------------------------------
# the happy ramp
# ---------------------------------------------------------------------------

def test_ramp_walks_declared_steps_monotone():
    world = World()
    desired = {E1: 200}
    state, _ = drive(SPEC, desired, world, state=RolloutState())
    assert state.phase == PHASE_COMPLETED
    assert world.observed[E1] == 200
    seq = [w[E1] for w in world.writes]
    assert seq == [10, 50, 100, 200]          # 5/25/50/100% of 200
    assert seq == sorted(seq), "weights must be monotone"


def test_ramp_interpolates_from_observed_baseline():
    """A re-weight 100 -> 200 ramps BETWEEN the two, never through 0."""
    world = World({E1: 100})
    state, _ = drive(SPEC, {E1: 200}, world, state=RolloutState())
    seq = [w[E1] for w in world.writes]
    assert seq == [105, 125, 150, 200]
    assert min(seq) >= 100


def test_multi_endpoint_vector_ramps_together():
    world = World({E1: 0})
    state, _ = drive(SPEC, {E1: 100, E2: 60}, world,
                     state=RolloutState())
    assert world.observed == {E1: 100, E2: 60}
    for w in world.writes:
        assert set(w) == {E1, E2}


def test_already_converged_completes_without_writes():
    world = World({E1: 200})
    state, _ = drive(SPEC, {E1: 200}, world, state=RolloutState())
    assert state.phase == PHASE_COMPLETED
    assert world.writes == []


def test_completed_target_drift_snaps_not_ramps():
    """Out-of-band drift against a COMPLETED target is repaired by one
    immediate write of the known-good weights — never a new ramp."""
    world = World({E1: 200})
    state, now = drive(SPEC, {E1: 200}, world, state=RolloutState())
    world.observed[E1] = 7                      # the drift
    out = advance(SPEC, state, {E1: 200}, dict(world.observed), now,
                  0)
    assert out.state is None and out.write == {E1: 200}


def test_new_target_after_completion_ramps_again():
    world = World()
    state, now = drive(SPEC, {E1: 200}, world, state=RolloutState())
    state2, _ = drive(SPEC, {E1: 400}, world, state=state, now=now)
    assert state2.phase == PHASE_COMPLETED
    seq = [w[E1] for w in world.writes]
    assert seq == sorted(seq)
    assert world.observed[E1] == 400


# ---------------------------------------------------------------------------
# the resumability matrix
# ---------------------------------------------------------------------------

def test_kill_restart_at_every_boundary_stays_monotone():
    """Crash (= state serialization round-trip, everything else
    forgotten) between every pair of turns: the write sequence is
    IDENTICAL to the crash-free run — monotone, no re-snap to the
    target, no duplicate writes."""
    clean = World()
    drive(SPEC, {E1: 200}, clean, state=RolloutState())
    crashy = World()
    drive(SPEC, {E1: 200}, crashy, state=RolloutState(),
          crash_every_turn=True)
    assert crashy.writes == clean.writes


@pytest.mark.parametrize("kill_after_writes", [1, 2, 3])
def test_crash_after_status_before_weights_resumes_forward(
        kill_after_writes):
    """The worst kill point: a step transition PERSISTED but its
    weights never written.  The successor must write the persisted
    step's weights (forward), never the final target and never the
    previous step (no revert-then-rejump)."""
    world = World()
    state = RolloutState()
    now = 0.0
    writes_seen = 0
    pending_write = None
    while writes_seen < kill_after_writes:
        out = advance(SPEC, state, {E1: 200}, dict(world.observed),
                      now, 0)
        if out.state is not None:
            state = out.state
        if out.write is not None:
            writes_seen += 1
            if writes_seen == kill_after_writes:
                pending_write = dict(out.write)
                break               # CRASH: status persisted, write lost
            world.apply(out)
        now += max(out.requeue_after, 0.01)
    state = crash(state)
    out = advance(SPEC, state, {E1: 200}, dict(world.observed), now, 0)
    assert out.write == pending_write, \
        "resume must re-issue exactly the persisted step's weights"
    assert out.state is None, "resume is a converge, not a transition"


def test_resume_on_converged_step_issues_zero_writes():
    """Crash AFTER a step's weights landed: the successor observes
    converged weights and writes NOTHING until the bake elapses."""
    world = World()
    state = RolloutState()
    out = advance(SPEC, state, {E1: 200}, {}, 0.0, 0)
    state = crash(out.state)
    world.apply(out)                              # step 0 landed (10)
    # successor wakes mid-bake
    out2 = advance(SPEC, state, {E1: 200}, dict(world.observed), 3.0, 0)
    assert out2.write is None and out2.state is None
    assert out2.requeue_after == pytest.approx(7.0)
    # ...and after the bake it advances to step 1, not to 100%
    out3 = advance(SPEC, state, {E1: 200}, dict(world.observed), 11.0, 0)
    assert out3.state.step == 1
    assert out3.write == {E1: 50}


def test_shard_handoff_resume_new_token_continues_and_stamps():
    """A successor presenting a HIGHER fencing token resumes the
    persisted step and stamps its own token on the next transition."""
    world = World()
    state = RolloutState()
    out = advance(SPEC, state, {E1: 200}, {}, 0.0, token=3)
    state = crash(out.state)
    world.apply(out)
    assert state.token == 3
    out2 = advance(SPEC, state, {E1: 200}, dict(world.observed), 11.0,
                   token=7)
    assert out2.state.step == 1 and out2.state.token == 7


def test_stale_fencing_token_transition_rejected():
    out = advance(SPEC, RolloutState(), {E1: 200}, {}, 0.0, token=5)
    state = crash(out.state)
    with pytest.raises(StaleRolloutTokenError):
        advance(SPEC, state, {E1: 200}, {E1: 10}, 11.0, token=4)


# ---------------------------------------------------------------------------
# health gate + rollback
# ---------------------------------------------------------------------------

def test_degraded_health_holds_step_never_advances():
    world = World()
    out = advance(SPEC, RolloutState(), {E1: 200}, {}, 0.0, 0)
    state = out.state
    world.apply(out)
    out2 = advance(SPEC, state, {E1: 200}, dict(world.observed), 20.0,
                   0, health=Health(HEALTH_DEGRADED, "circuit: open"))
    assert out2.state is None and out2.write is None
    assert out2.hold_reason == "circuit: open"
    assert out2.requeue_after > 0


def test_failed_health_rolls_back_exactly_once_and_sticks():
    world = World({E1: 100})
    # ramp two steps up from 100 toward 200
    state = RolloutState()
    now = 0.0
    for _ in range(2):
        out = advance(SPEC, state, {E1: 200}, dict(world.observed),
                      now, 0)
        if out.state is not None:
            state = out.state
        world.apply(out)
        now += max(out.requeue_after, 0.01)
    assert state.phase == PHASE_PROGRESSING
    failed = Health(HEALTH_FAILED, "abort: canary 500s")
    out = advance(SPEC, state, {E1: 200}, dict(world.observed), now, 0,
                  health=failed)
    assert out.transition == "rollback"
    assert out.state.phase == PHASE_ROLLING_BACK
    assert out.state.reason == "abort: canary 500s"
    state = crash(out.state)
    world.apply(out)
    assert world.observed[E1] == 100, "rollback restores the baseline"
    # duplicate deliveries: converge to RolledBack, NO second rollback
    # transition, no further writes
    writes_before = len(world.writes)
    out2 = advance(SPEC, state, {E1: 200}, dict(world.observed), now,
                   0, health=failed)
    assert out2.transition == "rolled_back"
    state = crash(out2.state)
    for _ in range(3):
        out3 = advance(SPEC, state, {E1: 200}, dict(world.observed),
                       now, 0, health=failed)
        assert out3.state is None and out3.write is None
        assert out3.transition is None
    assert len(world.writes) == writes_before
    assert state.phase == PHASE_ROLLED_BACK
    # sticky: even with health back to OK the failed target is dead...
    out4 = advance(SPEC, state, {E1: 200}, dict(world.observed), now,
                   0)
    assert out4.write is None and out4.hold == {E1: 100}
    # ...until a NEW target re-arms the machine
    state5, _ = drive(SPEC, {E1: 150}, world, state=state, now=now)
    assert state5.phase == PHASE_COMPLETED
    assert world.observed[E1] == 150


def test_rolled_back_drift_repaired_by_immediate_write():
    """RolledBack is sticky for the failed target, but NOT inert: an
    out-of-band edit that drifts the observed weights away from the
    rolled-back baseline is repaired by one immediate write of the
    last good weights (the Completed branch's drift semantics — the
    EGB plane mutates only from ``write``, so a hold-only outcome
    would leave the drifted group wrong forever)."""
    world = World({E1: 100})
    state = RolloutState()
    now = 0.0
    for _ in range(2):      # mid-ramp: Progressing past step 0
        out = advance(SPEC, state, {E1: 200}, dict(world.observed),
                      now, 0)
        if out.state is not None:
            state = out.state
        world.apply(out)
        now += max(out.requeue_after, 0.01)
    out = advance(SPEC, state, {E1: 200}, dict(world.observed), now,
                  0, health=Health(HEALTH_FAILED, "abort: x"))
    state = crash(out.state)
    world.apply(out)
    out2 = advance(SPEC, state, {E1: 200}, dict(world.observed),
                   now + 1.0, 0)
    state = crash(out2.state)
    assert state.phase == PHASE_ROLLED_BACK
    # the out-of-band edit
    world.observed[E1] = 7
    out3 = advance(SPEC, state, {E1: 200}, dict(world.observed), 102.0,
                   0)
    assert out3.write == {E1: 100}, \
        "rolled-back drift must be repaired, not held forever"
    assert out3.state is None, "no new transition for a drift repair"
    world.apply(out3)
    # converged again: back to hold-only, still sticky
    out4 = advance(SPEC, state, {E1: 200}, dict(world.observed), 103.0,
                   0)
    assert out4.write is None and out4.hold == {E1: 100}


def test_rollback_write_idempotent_when_already_at_baseline():
    """A rollback whose observed weights already equal the baseline
    (the step-0 failure shape) writes nothing."""
    world = World({E1: 100})
    out = advance(SPEC, RolloutState(), {E1: 200},
                  dict(world.observed), 0.0, 0)
    state = out.state                   # step 0 persisted (write 105)
    # CRASH before the write: observed still 100 == baseline
    out2 = advance(SPEC, crash(state), {E1: 200}, dict(world.observed),
                   1.0, 0, health=Health(HEALTH_FAILED, "abort: x"))
    assert out2.transition == "rollback"
    assert out2.write is None


# ---------------------------------------------------------------------------
# spec / state parsing + engine composition
# ---------------------------------------------------------------------------

def test_parse_spec_shapes():
    assert parse_spec({}) is None
    ok = parse_spec({ROLLOUT_STEPS_ANNOTATION: "5,25,50,100",
                     ROLLOUT_INTERVAL_ANNOTATION: "12"})
    assert ok.steps == (5, 25, 50, 100) and ok.interval == 12.0
    # a ramp that stops short is completed to 100
    assert parse_spec(
        {ROLLOUT_STEPS_ANNOTATION: "10,50"}).steps == (10, 50, 100)
    # malformed -> None (snap semantics), never a guess
    for bad in ("abc", "50,25", "0,100", "10,10", "5,120", ""):
        assert parse_spec({ROLLOUT_STEPS_ANNOTATION: bad}) is None
    assert parse_spec({ROLLOUT_STEPS_ANNOTATION: "50,100",
                       ROLLOUT_INTERVAL_ANNOTATION: "nope"}) is None
    assert parse_spec({ROLLOUT_STEPS_ANNOTATION: "50,100",
                       ROLLOUT_INTERVAL_ANNOTATION: "-1"}) is None


def test_state_json_roundtrip_and_garbage():
    st = RolloutState(phase=PHASE_PROGRESSING, step=2,
                      step_started_at=123.5, target_digest="abc",
                      from_weights={E1: 0}, to_weights={E1: 200},
                      token=9, generation=4, reason="r",
                      updated_at=124.0)
    assert RolloutState.from_json(st.to_json()) == st
    assert RolloutState.from_json(None) == RolloutState()
    assert RolloutState.from_json("{not json") == RolloutState()
    assert rollout_active(st.to_dict())
    assert not rollout_active(None)


def test_planned_weights_none_target_never_ramps():
    st = RolloutState(from_weights={E1: 0}, to_weights={E1: None})
    assert planned_weights(st, SPEC, 0) == {E1: None}


def _engine(**kw):
    return RolloutEngine("test-controller", registry=Registry(), **kw)


def test_engine_abort_annotation_is_terminal_even_health_none():
    eng = _engine()
    spec = RolloutSpec(health="none")
    h = eng.health_for("k", spec, {ROLLOUT_ABORT_ANNOTATION: "bad"})
    assert h.verdict == HEALTH_FAILED and "bad" in h.reason


def test_engine_breaker_and_error_window_degrade_gated_only():
    eng = _engine(region_health=lambda: (False, "circuit: r open"))
    gated = RolloutSpec(health="gated", interval=10.0)
    assert eng.health_for("k", gated, {}).verdict == HEALTH_DEGRADED
    assert eng.health_for(
        "k", RolloutSpec(health="none"), {}).verdict == "healthy"
    ok = _engine(region_health=lambda: (True, ""))
    assert ok.health_for("k", gated, {}).verdict == "healthy"
    ok.note_error("k")
    assert ok.health_for("k", gated, {}).verdict == HEALTH_DEGRADED
    ok.note_ok("k")
    assert ok.health_for("k", gated, {}).verdict == "healthy"


def test_engine_decide_passthrough_without_annotations():
    eng = _engine()
    out = eng.decide(key="k", route="k", annotations={},
                     state_dict=None, desired={E1: 7}, observed={})
    assert out.write == {E1: 7} and out.state is None
    out2 = eng.decide(key="k", route="k", annotations={},
                      state_dict=None, desired={E1: 7},
                      observed={E1: 7})
    assert out2.write is None


def test_engine_decide_none_weights_passthrough():
    """spec.weight: null ("leave the cloud default") cannot be
    interpolated — snap semantics even with a declared ramp."""
    eng = _engine()
    out = eng.decide(key="k", route="k",
                     annotations={ROLLOUT_STEPS_ANNOTATION: "50,100"},
                     state_dict=None, desired={E1: None}, observed={})
    assert out.write == {E1: None} and out.state is None


def test_engine_annotations_removed_mid_ramp_snaps_and_clears():
    eng = _engine()
    mid = RolloutState(phase=PHASE_PROGRESSING, step=1,
                       target_digest="x", from_weights={E1: 0},
                       to_weights={E1: 200})
    out = eng.decide(key="k", route="k", annotations={},
                     state_dict=mid.to_dict(), desired={E1: 200},
                     observed={E1: 50})
    assert out.write == {E1: 200}
    assert out.state is not None
    assert out.state.phase == PHASE_COMPLETED
    assert "removed" in out.state.reason
    assert not rollout_active(out.state.to_dict())


def test_engine_counts_transitions_holds_rollbacks():
    reg = Registry()
    eng = RolloutEngine("ctl", registry=reg)
    ann = {ROLLOUT_STEPS_ANNOTATION: "50,100",
           ROLLOUT_INTERVAL_ANNOTATION: "0.01"}
    out = eng.decide(key="k", route="k", annotations=ann,
                     state_dict=None, desired={E1: 100}, observed={})
    assert reg.counter_value("rollout_transitions_total",
                             {"controller": "ctl", "to": "start"}) == 1
    aborted = dict(ann)
    aborted[ROLLOUT_ABORT_ANNOTATION] = "canary 500s"
    out2 = eng.decide(key="k", route="k", annotations=aborted,
                      state_dict=out.state.to_dict(),
                      desired={E1: 100}, observed={E1: 50})
    assert out2.transition == "rollback"
    assert reg.counter_value("rollout_rollbacks_total",
                             {"controller": "ctl",
                              "reason": "abort"}) == 1


def test_route53_worker_wrapper_feeds_rollout_health_gate():
    """The Route53 worker loop's process-func wrapper is the
    controller's only feed into the engine's sync-error window: an
    exception marks the key degraded, a completed sync clears it —
    without it the 'sync_errors' half of the record-plane health gate
    would be inert."""
    from aws_global_accelerator_controller_tpu.controller.route53 import (
        Route53Controller,
    )

    c = Route53Controller.__new__(Route53Controller)
    c.rollout = RolloutEngine("r53-test")

    class Obj:
        def key(self):
            return "default/x"

    def boom(arg):
        raise RuntimeError("sync failed")

    with pytest.raises(RuntimeError):
        c._rollout_health_tracked(boom)("default/x")
    assert c.rollout._recent_error("default/x", 60.0), \
        "a failed sync must open the health window"

    c._rollout_health_tracked(lambda arg: None)(Obj())
    assert not c.rollout._recent_error("default/x", 60.0), \
        "a completed sync must clear the health window"
